"""Per-device-kind performance floors (probe/floors.py).

The gap these tests pin (VERDICT r03 #1): the probe *measured*
matmul_tflops / int8_tops / hbm_gbps / ring_link_gbps but nothing *graded*
them, so a thermally-throttled chip at 10 % of peak passed every numerics
gate.  Floors grade each figure against an operator-tunable fraction of the
generation's published peak; TNC_CHAOS_THROTTLE rehearses the failure on
healthy hardware; TNC_PERF_EXPECT overrides the table (and is the CPU-mesh
test path, since the built-in table grades only real TPU silicon).
"""

import json

import pytest

from tpu_node_checker.probe.floors import (
    CHIP_SPECS,
    DEFAULT_FLOOR_FRACTION,
    FLOOR_METRICS,
    HBM_CAPACITY_GB,
    floor_failure_message,
    grade_floors,
    grade_hbm_capacity,
)
from tpu_node_checker.probe.liveness import run_local_probe


class TestGradeFloors:
    def test_v5e_at_full_speed_passes(self):
        spec = CHIP_SPECS["v5e"]
        measured = {m: spec[m] * 0.8 for m in spec}
        v = grade_floors(["TPU v5 lite"], "tpu", measured)
        assert v["ok"] is True
        assert v["generation"] == "v5e"
        assert v["failed"] == []
        assert v["fraction"] == DEFAULT_FLOOR_FRACTION
        assert v["ratios"]["matmul_tflops"] == pytest.approx(0.8, abs=1e-3)

    def test_throttled_chip_fails_naming_the_metric(self):
        spec = CHIP_SPECS["v5e"]
        measured = {m: spec[m] * 0.8 for m in spec}
        measured["matmul_tflops"] = spec["matmul_tflops"] * 0.1  # throttled MXU
        v = grade_floors(["TPU v5e"], "tpu", measured)
        assert v["ok"] is False
        assert v["failed"] == ["matmul_tflops"]
        msg = floor_failure_message(v)
        assert msg.startswith("perf_floor: ")
        assert "matmul_tflops" in msg and "v5e" in msg

    def test_fraction_is_tunable(self):
        spec = CHIP_SPECS["v5p"]
        measured = {"matmul_tflops": spec["matmul_tflops"] * 0.5}
        assert grade_floors(["TPU v5p"], "tpu", measured, fraction=0.4)["ok"]
        assert not grade_floors(["TPU v5p"], "tpu", measured, fraction=0.6)["ok"]

    def test_zero_fraction_disables(self):
        v = grade_floors(["TPU v5e"], "tpu", {"matmul_tflops": 0.001}, fraction=0)
        assert "skipped" in v and "disabled" in v["skipped"]

    def test_off_tpu_skipped_with_reason(self):
        v = grade_floors(["cpu"], "cpu", {"matmul_tflops": 0.1})
        assert "skipped" in v and "cpu" in v["skipped"]

    def test_unknown_or_mixed_kinds_skip_never_guess(self):
        # Vague ("TPU v6"), unknown, and mixed-generation kind lists must
        # skip: grading against the wrong spec sheet could floor-fail (or
        # pass) a fleet on a rename.
        for kinds in (["TPU v6"], ["TPU v99"], ["TPU v4", "TPU v5e"], [], None):
            v = grade_floors(kinds, "tpu", {"matmul_tflops": 500.0})
            assert "skipped" in v, kinds

    def test_only_overlapping_metrics_grade(self):
        # v2 has no int8 spec; a measured int8 figure must not fail it, and
        # an unmeasured ring must not fail anything.
        v = grade_floors(
            ["TPU v2"], "tpu", {"matmul_tflops": 40.0, "int8_tops": 0.001}
        )
        assert v["ok"] is True
        assert set(v["ratios"]) == {"matmul_tflops"}

    def test_non_finite_and_non_numeric_measurements_ignored(self):
        v = grade_floors(
            ["TPU v5e"],
            "tpu",
            {"matmul_tflops": float("nan"), "hbm_gbps": "fast", "int8_tops": 380.0},
        )
        assert set(v["ratios"]) == {"int8_tops"}
        assert v["ok"] is True

    def test_explicit_expectations_grade_any_platform(self):
        v = grade_floors(
            None, "cpu", {"matmul_tflops": 0.05},
            expectations={"matmul_tflops": 0.05},
        )
        assert v["ok"] is True and v["generation"] == "custom"
        v = grade_floors(
            None, "cpu", {"matmul_tflops": 0.001},
            expectations={"matmul_tflops": 1e9},
        )
        assert v["ok"] is False and v["failed"] == ["matmul_tflops"]

    def test_expectations_with_no_known_metric_skip(self):
        v = grade_floors(None, "cpu", {"matmul_tflops": 1.0},
                         expectations={"bogus": 5})
        assert "skipped" in v

    def test_throttle_fails_a_healthy_chip(self):
        spec = CHIP_SPECS["v6e"]
        measured = {m: spec[m] * 0.9 for m in spec}
        v = grade_floors(["TPU v6e"], "tpu", measured, throttle="hbm_gbps")
        assert v["ok"] is False
        assert v["failed"] == ["hbm_gbps"]
        assert v["throttled"] == ["hbm_gbps"]
        # 0.9 / 20 = 0.045 of peak
        assert v["ratios"]["hbm_gbps"] == pytest.approx(0.045, abs=1e-3)

    def test_throttle_all(self):
        spec = CHIP_SPECS["v4"]
        measured = {m: spec[m] * 0.9 for m in spec}
        v = grade_floors(["TPU v4"], "tpu", measured, throttle="all")
        assert v["ok"] is False
        assert v["failed"] == sorted(spec)
        assert v["throttled"] == sorted(spec)

    def test_throttle_never_injects_silently(self):
        # Unknown metric name, grading skipped (off-tpu / disabled), or
        # metric not measured: each must raise, not pass while testing
        # nothing.
        with pytest.raises(ValueError, match="TNC_CHAOS_THROTTLE"):
            grade_floors(["TPU v5e"], "tpu", {"matmul_tflops": 100.0},
                         throttle="warp_speed")
        with pytest.raises(ValueError, match="skipped"):
            grade_floors(["cpu"], "cpu", {"matmul_tflops": 0.1},
                         throttle="matmul_tflops")
        with pytest.raises(ValueError, match="skipped"):
            grade_floors(["TPU v5e"], "tpu", {"matmul_tflops": 100.0},
                         fraction=0, throttle="matmul_tflops")
        with pytest.raises(ValueError, match="not measured"):
            grade_floors(["TPU v5e"], "tpu", {"matmul_tflops": 100.0},
                         throttle="ring_link_gbps")

    def test_pathological_dispatch_overhead_skips_table_grading(self):
        # Remote/tunneled PJRT transports add tens of ms per call; the
        # wall-clock figures then measure the transport, not the chip —
        # grading the table against them would floor-fail healthy silicon.
        spec = CHIP_SPECS["v5e"]
        measured = {"matmul_tflops": spec["matmul_tflops"] * 0.02}
        v = grade_floors(["TPU v5e"], "tpu", measured, dispatch_overhead_ms=65.0)
        assert "skipped" in v and "dispatch overhead" in v["skipped"]
        # In-pod dispatch (µs) grades normally.
        v = grade_floors(["TPU v5e"], "tpu", measured, dispatch_overhead_ms=0.05)
        assert v["ok"] is False

    def test_max_dispatch_env_parse(self):
        # Presence and value parse apart (r4 advisor): absent/empty → None
        # (built-in 5 ms gate); an explicit 0 → inf, DISABLING the gate —
        # the old `or 0 ... or None` made that impossible; a typo names the
        # var like TNC_PERF_FLOOR's parse does.
        import math

        from tpu_node_checker.probe.floors import max_dispatch_from_env

        assert max_dispatch_from_env(None) is None
        assert max_dispatch_from_env("  ") is None
        assert max_dispatch_from_env("12.5") == 12.5
        assert max_dispatch_from_env("0") == math.inf
        assert max_dispatch_from_env("-3") == math.inf
        assert max_dispatch_from_env("inf") == math.inf
        with pytest.raises(ValueError, match="TNC_PERF_FLOOR_MAX_DISPATCH_MS"):
            max_dispatch_from_env("fast")
        # NaN parses as a float but would disable the gate silently (every
        # `>` comparison is False) — rejected like a typo, not passed through.
        with pytest.raises(ValueError, match="TNC_PERF_FLOOR_MAX_DISPATCH_MS"):
            max_dispatch_from_env("nan")
        # And inf actually disables: tunneled-transport overhead no longer
        # skips table grading, so a throttled chip still fails the floor.
        spec = CHIP_SPECS["v5e"]
        measured = {"matmul_tflops": spec["matmul_tflops"] * 0.02}
        v = grade_floors(
            ["TPU v5e"], "tpu", measured,
            dispatch_overhead_ms=65.0, max_dispatch_ms=math.inf,
        )
        assert v["ok"] is False and v["failed"] == ["matmul_tflops"]

    def test_explicit_expectations_bypass_dispatch_gate(self):
        # TNC_PERF_EXPECT means the operator calibrated for their transport.
        v = grade_floors(
            ["TPU v5e"], "tpu", {"matmul_tflops": 3.8},
            expectations={"matmul_tflops": 4.0},
            dispatch_overhead_ms=65.0,
        )
        assert v["ok"] is True and v["generation"] == "custom"

    def test_sustained_tflops_grades_against_bf16_peak(self):
        # A chip that passes the cold one-shot burn but throttles over the
        # soak: sustained median is graded against the same bf16 peak.
        spec = CHIP_SPECS["v5e"]
        v = grade_floors(
            ["TPU v5e"], "tpu",
            {"matmul_tflops": spec["matmul_tflops"] * 0.8,
             "sustained_tflops": spec["matmul_tflops"] * 0.1},
        )
        assert v["ok"] is False
        assert v["failed"] == ["sustained_tflops"]
        assert v["expected"]["sustained_tflops"] == spec["matmul_tflops"]
        msg = floor_failure_message(v)
        assert "sustained_tflops" in msg

    def test_sustained_alias_never_applies_to_custom_expectations(self):
        # TNC_PERF_EXPECT naming only matmul_tflops means "grade the cold
        # burn": the alias must not volunteer sustained grading the
        # operator never asked for.
        v = grade_floors(
            None, "cpu",
            {"matmul_tflops": 60.0, "sustained_tflops": 0.001},
            expectations={"matmul_tflops": 50.0},
        )
        assert v["ok"] is True
        assert set(v["ratios"]) == {"matmul_tflops"}
        # Naming it explicitly still grades it.
        v = grade_floors(
            None, "cpu",
            {"sustained_tflops": 0.001},
            expectations={"sustained_tflops": 50.0},
        )
        assert v["ok"] is False and v["failed"] == ["sustained_tflops"]

    def test_every_generation_spec_is_sane(self):
        for gen, spec in CHIP_SPECS.items():
            assert spec.keys() <= set(FLOOR_METRICS), gen
            assert all(v > 0 for v in spec.values()), gen

    def test_v2_v3_floors_are_per_core_device(self):
        # On v2/v3 a JAX device is a TensorCore with half the chip's MXUs
        # and HBM channels (r4 advisor, medium): CHIP_SPECS must store
        # per-DEVICE peaks — half the published per-chip 45/123 TFLOPs and
        # 700/900 GB/s — exactly as HBM_CAPACITY_GB halves capacity.  A
        # healthy core at ~60% of its real per-core peak must pass the 0.4
        # floor, not get quarantined for being half a chip.
        assert CHIP_SPECS["v2"] == {"matmul_tflops": 22.5, "hbm_gbps": 350.0}
        assert CHIP_SPECS["v3"] == {"matmul_tflops": 61.5, "hbm_gbps": 450.0}
        v = grade_floors(
            ["TPU v3"], "tpu",
            {"matmul_tflops": 0.6 * 61.5, "hbm_gbps": 0.6 * 450.0},
        )
        assert v["ok"] is True, v
        # A genuinely throttled core (10% of per-core peak) still fails.
        v = grade_floors(["TPU v3"], "tpu", {"matmul_tflops": 6.15})
        assert v["ok"] is False and v["failed"] == ["matmul_tflops"]


class TestHbmCapacity:
    """Capacity grading: a chip exposing half its HBM is sick even when
    every throughput and numerics gate passes — and unlike timing floors,
    bytes_limit is transport-insensitive."""

    def _mem(self, *gb, ids=None):
        return [
            {"id": ids[i] if ids else i, "bytes_in_use": 0,
             "bytes_limit": int(g * 1e9)}
            for i, g in enumerate(gb)
        ]

    def test_healthy_chips_pass_with_runtime_reservation(self):
        # A ~7% runtime carve-out off the 16 GB nominal must pass.
        v = grade_hbm_capacity(["TPU v5e"], "tpu", self._mem(14.9, 15.1, 15.0, 14.9))
        assert v["ok"] is True
        assert v["generation"] == "v5e"
        assert v["min_gb"] == 14.9
        assert v["failed_devices"] == []

    def test_half_hbm_chip_fails_naming_the_device(self):
        v = grade_hbm_capacity(["TPU v5e"], "tpu", self._mem(15.5, 8.0, 15.6, 15.5))
        assert v["ok"] is False
        assert v["failed_devices"] == [{"id": 1, "gb": 8.0}]

    def test_zero_limit_chip_fails_not_slips_through(self):
        # The worst case — a chip exposing NO HBM while its peers are
        # healthy — must fail at 0, not vanish from the parse.
        v = grade_hbm_capacity(
            ["TPU v5e"], "tpu",
            self._mem(15.5, 15.6) + [{"id": 2, "bytes_limit": 0},
                                     {"id": 3, "bytes_limit": None}],
        )
        assert v["ok"] is False
        assert v["failed_devices"] == [{"id": 2, "gb": 0.0}, {"id": 3, "gb": 0.0}]
        assert v["min_gb"] == 0.0

    def test_v2_v3_capacity_is_per_core_device(self):
        # On v2/v3 a JAX device is a TensorCore with HALF the chip's HBM;
        # a healthy v2 core (~7.5 GB of its 8 GB) must pass.
        v = grade_hbm_capacity(["TPU v2"], "tpu", self._mem(7.5, 7.5))
        assert v["ok"] is True, v
        v = grade_hbm_capacity(["TPU v3"], "tpu", self._mem(15.0))
        assert v["ok"] is True, v

    def test_skips_visibly(self):
        assert "skipped" in grade_hbm_capacity(["cpu"], "cpu", self._mem(16))
        assert "skipped" in grade_hbm_capacity(["TPU v99"], "tpu", self._mem(16))
        assert "skipped" in grade_hbm_capacity(["TPU v5e"], "tpu", [])
        # ALL limits absent (None) = runtime without memory_stats: skip.
        assert "skipped" in grade_hbm_capacity(
            ["TPU v5e"], "tpu", [{"id": 0, "bytes_limit": None}]
        )
        assert "skipped" in grade_hbm_capacity(
            ["TPU v5e"], "tpu", self._mem(1.0), fraction=0
        )

    def test_all_zero_limits_fail_not_skip(self):
        # Explicit zeros are REPORTS: every chip exposing 0 GB is the worst
        # uniform fault, not a missing-stats runtime — it must fail.
        v = grade_hbm_capacity(
            ["TPU v5e"], "tpu",
            [{"id": 0, "bytes_limit": 0}, {"id": 1, "bytes_limit": 0}],
        )
        assert v["ok"] is False
        assert len(v["failed_devices"]) == 2

    def test_every_generation_has_capacity(self):
        assert set(HBM_CAPACITY_GB) == set(CHIP_SPECS)
        assert all(v > 0 for v in HBM_CAPACITY_GB.values())


class TestFloorsInProbeChild:
    """End-to-end through the subprocess child on the CPU mesh."""

    def test_off_tpu_grading_is_stamped_skipped(self, shared_compute_probe):
        # CPU platform, no explicit expectations: the verdict must say WHY
        # floors did not grade — visible, never silent.
        floor = shared_compute_probe.details.get("perf_floor")
        assert floor and "cpu" in floor["skipped"]

    def test_expectation_override_grades_and_fails(self, monkeypatch):
        monkeypatch.setenv("TNC_PERF_EXPECT", json.dumps({"matmul_tflops": 1e9}))
        r = run_local_probe(level="compute", timeout_s=300)
        assert not r.ok
        assert "perf_floor" in (r.error or "")
        assert "matmul_tflops" in (r.error or "")
        floor = r.details["perf_floor"]
        assert floor["failed"] == ["matmul_tflops"]
        assert floor["generation"] == "custom"

    def test_chaos_throttle_fails_healthy_host_with_named_metric(
        self, monkeypatch, shared_compute_probe
    ):
        # Learn this machine's real figure (from the shared clean child),
        # then expect exactly it: the un-throttled chip passes (measured ≈
        # expected > 0.4×expected) and the throttled rehearsal (÷20) fails
        # naming the metric.
        measured = shared_compute_probe.details["matmul_tflops"]
        monkeypatch.setenv(
            "TNC_PERF_EXPECT", json.dumps({"matmul_tflops": measured})
        )
        clean = run_local_probe(level="compute", timeout_s=300)
        assert clean.ok, clean.error
        assert clean.details["perf_floor"]["ok"] is True
        monkeypatch.setenv("TNC_CHAOS_THROTTLE", "matmul_tflops")
        throttled = run_local_probe(level="compute", timeout_s=300)
        assert not throttled.ok
        floor = throttled.details["perf_floor"]
        assert floor["failed"] == ["matmul_tflops"]
        assert floor["throttled"] == ["matmul_tflops"]
        assert "perf_floor" in (throttled.error or "")
        assert throttled.details["chaos_injected"] == {"throttle": "matmul_tflops"}

    def test_throttle_at_enumerate_level_fails_loudly(self, monkeypatch):
        monkeypatch.setenv("TNC_CHAOS_THROTTLE", "matmul_tflops")
        r = run_local_probe(level="enumerate", timeout_s=300)
        assert not r.ok
        assert r.details.get("chaos_injected") == {"throttle": "matmul_tflops"}
        assert "TNC_CHAOS_THROTTLE" in (r.error or "")

    @pytest.mark.slow  # own probe child(ren); CI's slow step covers it
    def test_soak_median_graded_as_sustained(self, monkeypatch):
        # End-to-end wiring: a short soak's tflops_median feeds floor
        # grading as sustained_tflops when the expectations name it.
        monkeypatch.setenv(
            "TNC_PERF_EXPECT", json.dumps({"sustained_tflops": 1e9})
        )
        monkeypatch.setenv("TNC_SOAK_MIN_RATIO", "0")  # CPU jitter
        r = run_local_probe(level="compute", timeout_s=400, soak_s=1.0)
        assert not r.ok
        floor = r.details["perf_floor"]
        assert floor["failed"] == ["sustained_tflops"]
        assert floor["measured"]["sustained_tflops"] > 0
        assert "sustained_tflops" in (r.error or "")

    @pytest.mark.slow  # own probe child(ren); CI's slow step covers it
    def test_malformed_floor_env_vars_name_the_var(self, monkeypatch):
        # A config typo must read as a config typo, not a hardware fault —
        # --cordon-failed acts on probe failures.
        monkeypatch.setenv("TNC_HBM_CAPACITY_FLOOR", "ten")
        r = run_local_probe(level="enumerate", timeout_s=120)
        assert not r.ok
        assert "TNC_HBM_CAPACITY_FLOOR" in (r.error or "")
        monkeypatch.delenv("TNC_HBM_CAPACITY_FLOOR")
        monkeypatch.setenv("TNC_PERF_FLOOR", "0.4%")
        r = run_local_probe(level="compute", timeout_s=300)
        assert not r.ok
        assert "TNC_PERF_FLOOR" in (r.error or "")
        monkeypatch.delenv("TNC_PERF_FLOOR")
        monkeypatch.setenv("TNC_PERF_FLOOR_MAX_DISPATCH_MS", "fast")
        r = run_local_probe(level="compute", timeout_s=300)
        assert not r.ok
        assert "TNC_PERF_FLOOR_MAX_DISPATCH_MS" in (r.error or "")

    @pytest.mark.slow  # own probe child(ren); CI's slow step covers it
    def test_perf_floor_zero_disables_via_flag_plumbing(self, monkeypatch):
        monkeypatch.setenv("TNC_PERF_EXPECT", json.dumps({"matmul_tflops": 1e9}))
        r = run_local_probe(level="compute", timeout_s=300, perf_floor=0)
        assert r.ok, r.error
        assert "disabled" in r.details["perf_floor"]["skipped"]


class TestFloorsCliAndMetrics:
    def test_flag_combinations_validated(self, capsys):
        from tpu_node_checker import cli

        for argv in (
            ["--perf-floor", "0.4"],  # no probe source
            ["--probe", "--perf-floor", "0.4"],  # enumerate level
            ["--probe", "--probe-level", "compute", "--perf-floor", "-1"],
        ):
            with pytest.raises(SystemExit) as exc:
                cli.parse_args(argv)
            assert exc.value.code == 2, argv
            capsys.readouterr()
        args = cli.parse_args(
            ["--probe", "--probe-level", "compute", "--perf-floor", "0.5"]
        )
        assert args.perf_floor == 0.5

    def test_metrics_export_floor_families(self):
        from tpu_node_checker.checker import CheckResult
        from tpu_node_checker.metrics import render_metrics

        result = CheckResult(exit_code=0)
        result.payload = {
            "total_nodes": 1, "ready_nodes": 1, "slices": [],
            "local_probe": {
                "ok": False, "level": "compute",
                "perf_floor": {
                    "generation": "v5e", "fraction": 0.4,
                    "ratios": {"matmul_tflops": 0.1, "hbm_gbps": 0.8},
                    "failed": ["matmul_tflops"], "ok": False,
                },
            },
            "timings_ms": {"total": 1.0},
        }
        text = render_metrics(result)
        assert 'tpu_node_checker_probe_perf_floor_ok{generation="v5e"} 0.0' in text
        assert 'tpu_node_checker_probe_perf_floor_ratio{metric="matmul_tflops"} 0.1' in text
        assert 'tpu_node_checker_probe_perf_floor_ratio{metric="hbm_gbps"} 0.8' in text

    def test_human_output_renders_floor_verdict(self, capsys, monkeypatch):
        from tests import fixtures as fx
        from tpu_node_checker import checker, cli
        from tpu_node_checker.probe.liveness import ProbeResult

        monkeypatch.setenv("NODE_NAME", "gke-tpu-v5e-0")
        monkeypatch.setattr(
            checker,
            "run_local_probe",
            lambda **kw: ProbeResult(
                ok=False, level="compute", hostname="gke-tpu-v5e-0",
                elapsed_ms=1.0, device_count=4, platform="tpu",
                device_kinds=["TPU v5e"],
                error="perf_floor: matmul_tflops 19.7 < floor 78.8",
                details={"perf_floor": {
                    "generation": "v5e", "fraction": 0.4,
                    "expected": {"matmul_tflops": 197.0},
                    "measured": {"matmul_tflops": 19.7},
                    "ratios": {"matmul_tflops": 0.1},
                    "failed": ["matmul_tflops"], "ok": False,
                }},
            ),
            raising=False,
        )
        import tpu_node_checker.probe as probe_pkg

        monkeypatch.setattr(
            probe_pkg, "run_local_probe", checker.run_local_probe, raising=False
        )
        code = checker.one_shot(
            cli.parse_args(["--probe", "--probe-level", "compute"]),
            nodes=fx.tpu_v5e_single_host(),
        )
        assert code == 3  # floor failure demotes effective readiness
        out = capsys.readouterr().out
        assert "Perf floors: FAILED" in out
        assert "matmul_tflops" in out

    def test_fleet_rollup_separates_floor_failures(self, tmp_path):
        import json as _json

        from tests import fixtures as fx
        from tpu_node_checker import checker, cli
        from tpu_node_checker.metrics import render_metrics

        reports = tmp_path / "reports"
        reports.mkdir()
        # h0: dead (enumeration failed); h1: slow (floor failed); h2: ok.
        (reports / "gke-tpu-v5p-0.json").write_text(
            _json.dumps({"ok": False, "hostname": "gke-tpu-v5p-0",
                         "level": "compute", "error": "no chips"})
        )
        (reports / "gke-tpu-v5p-1.json").write_text(
            _json.dumps({
                "ok": False, "hostname": "gke-tpu-v5p-1", "level": "compute",
                "error": "perf_floor: matmul_tflops ...",
                "perf_floor": {"ok": False, "failed": ["matmul_tflops"],
                               "ratios": {"matmul_tflops": 0.1}},
            })
        )
        (reports / "gke-tpu-v5p-2.json").write_text(
            _json.dumps({"ok": True, "hostname": "gke-tpu-v5p-2",
                         "level": "compute"})
        )
        result = checker.run_check(
            cli.parse_args(["--probe-results", str(reports), "--json"]),
            nodes=fx.tpu_v5p_64_slice(),
        )
        summary = result.payload["probe_summary"]
        assert summary["hosts_failed"] == ["gke-tpu-v5p-0", "gke-tpu-v5p-1"]
        assert summary["hosts_floor_failed"] == ["gke-tpu-v5p-1"]
        text = render_metrics(result)
        assert 'tpu_node_checker_probe_hosts{state="floor_failed"} 1' in text

    def test_hbm_capacity_families(self):
        from tpu_node_checker.checker import CheckResult
        from tpu_node_checker.metrics import render_metrics

        result = CheckResult(exit_code=0)
        result.payload = {
            "total_nodes": 1, "ready_nodes": 1, "slices": [],
            "local_probe": {
                "ok": False, "level": "enumerate",
                "hbm_capacity": {
                    "generation": "v5e", "expected_gb": 16.0, "fraction": 0.9,
                    "min_gb": 8.0,
                    "failed_devices": [{"id": 1, "gb": 8.0}], "ok": False,
                },
            },
            "timings_ms": {"total": 1.0},
        }
        text = render_metrics(result)
        assert 'tpu_node_checker_probe_hbm_capacity_ok{generation="v5e"} 0.0' in text
        assert "tpu_node_checker_probe_hbm_min_gb 8.0" in text
        # A skipped stamp emits no capacity families.
        result.payload["local_probe"]["hbm_capacity"] = {"skipped": "x"}
        assert "hbm_capacity_ok" not in render_metrics(result)

    def test_skipped_grading_exports_no_floor_families(self):
        from tpu_node_checker.checker import CheckResult
        from tpu_node_checker.metrics import render_metrics

        result = CheckResult(exit_code=0)
        result.payload = {
            "total_nodes": 1, "ready_nodes": 1, "slices": [],
            "local_probe": {"ok": True, "level": "compute",
                            "perf_floor": {"skipped": "platform 'cpu'"}},
            "timings_ms": {"total": 1.0},
        }
        assert "perf_floor" not in render_metrics(result)


class TestRelistWorkFloor:
    """The relist fast path's cost floor, pinned as DETERMINISTIC work
    counters rather than wall clock (a loaded CI box must not flake a
    perf contract): a relist at N-node churn decodes and re-extracts
    exactly N nodes — the O(changes) property BENCH_r10's
    nodes5k_relist_churn1pct_p50_ms gate measures in milliseconds."""

    def _pages(self, nodes, page_size=500):
        import json as _json

        bodies = []
        for start in range(0, len(nodes), page_size):
            bodies.append(_json.dumps(
                {"kind": "NodeList", "items": nodes[start:start + page_size]}
            ).encode())
        return bodies

    def _walk(self, projector, bodies):
        from tpu_node_checker import fastpath

        class _Resp:
            def __init__(self, body):
                self.content = body

        items = []
        for i, body in enumerate(bodies):
            nodes, _ = projector.decode_page(_Resp(body), i)
            items.extend(nodes)
        return fastpath.ProjectedFleet(items, "1", projector.reuse)

    def test_zero_churn_relist_decodes_and_extracts_nothing(self):
        from tests import fixtures as fx
        from tpu_node_checker import fastpath

        nodes = [
            fx.make_node(f"floor-{i:04d}", allocatable={"google.com/tpu": "4"})
            for i in range(1000)
        ]
        projector = fastpath.ListProjector()
        fleet = self._walk(projector, self._pages(nodes))
        fleet.reuse.select(fleet, None)
        base = dict(projector.stats)
        extracts = fleet.reuse.extracts
        fleet2 = self._walk(projector, self._pages(nodes))
        fleet2.reuse.select(fleet2, None)
        assert projector.stats["items_decoded"] == base["items_decoded"]
        assert projector.stats["pages_unchanged"] - base["pages_unchanged"] == 2
        assert fleet2.reuse.extracts == extracts  # zero re-extraction

    def test_one_percent_churn_costs_exactly_the_churn(self):
        from tests import fixtures as fx
        from tpu_node_checker import fastpath

        nodes = [
            fx.make_node(f"floor-{i:04d}", allocatable={"google.com/tpu": "4"})
            for i in range(1000)
        ]
        projector = fastpath.ListProjector()
        fleet = self._walk(projector, self._pages(nodes))
        fleet.reuse.select(fleet, None)
        base = dict(projector.stats)
        extracts = fleet.reuse.extracts
        # A contiguous 10-node block flips Ready (one byte window: the
        # floor is exact; scattered churn only widens the decoded window,
        # never the re-extraction set).
        for n in nodes[100:110]:
            for cond in n["status"]["conditions"]:
                if cond["type"] == "Ready":
                    cond["status"] = "False"
        fleet2 = self._walk(projector, self._pages(nodes))
        changed = fleet2.reuse.select(fleet2, None)[3]
        assert projector.stats["items_decoded"] - base["items_decoded"] == 10
        assert projector.stats["items_reused"] - base["items_reused"] == 490
        assert fleet2.reuse.extracts - extracts == 10
        assert len(changed) == 10
