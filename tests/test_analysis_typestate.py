"""Typestate-tier tests (TNC114-117): the exception-escape fixpoint,
the release-obligation interpreter, the obligation-transfer matrix,
full-vs-incremental equivalence, and the SARIF surface."""

import json
from pathlib import Path

# Import the registry package FIRST: analysis/rules/__init__.py imports
# flow/rules.py which imports typestate.py back — importing typestate (or
# flow.rules) as the very first analysis import trips that cycle.
import tpu_node_checker.analysis.rules  # noqa: F401

from tpu_node_checker.analysis.cache import run_incremental
from tpu_node_checker.analysis.engine import load_project, run_project
from tpu_node_checker.analysis.flow.typestate import (
    AtomicWrite,
    ExceptionEscape,
    FinallyHygiene,
    MustRelease,
    covers,
    typestate_state,
)
from tpu_node_checker.analysis.sarif import SARIF_VERSION, render_sarif

CORPUS_ROOT = Path(__file__).resolve().parent / "analysis_fixtures" / "repo"

TYPESTATE_CODES = ("TNC114", "TNC115", "TNC116", "TNC117")


def _mini(tmp_path, files):
    """Write a miniature checkout; returns its root as str."""
    for rel, source in files.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(source)
    (tmp_path / "tpu_node_checker").mkdir(exist_ok=True)
    init = tmp_path / "tpu_node_checker" / "__init__.py"
    if not init.exists():
        init.write_text("")
    return str(tmp_path)


def _escapes(root):
    project = load_project(root)
    return project, typestate_state(project).escapes


def _rule_findings(rule, root):
    return sorted(
        (f.path, f.line) for f in rule.check_project(load_project(root))
    )


# -- the exception lattice -------------------------------------------------


class TestCovers:
    def test_builtin_ancestry(self):
        assert covers("OSError", "ConnectionResetError", {})
        assert covers("Exception", "KeyError", {})
        assert covers("BaseException", "KeyboardInterrupt", {})

    def test_siblings_do_not_cover(self):
        assert not covers("ValueError", "OSError", {})
        # Exception does NOT cover the BaseException-only branch.
        assert not covers("Exception", "SystemExit", {})

    def test_unknown_class_assumed_exception_child(self):
        # http.client.BadStatusLine isn't in the builtin table and isn't
        # a project class — the lattice parks it under Exception so a
        # catch-all handler subtracts it (documented soundness caveat).
        assert covers("Exception", "BadStatusLine", {})
        assert not covers("OSError", "BadStatusLine", {})

    def test_project_class_chain(self):
        parents = {"ShardError": {"OSError"}, "FleetError": {"ShardError"}}
        assert covers("OSError", "FleetError", parents)
        assert not covers("ValueError", "FleetError", parents)


# -- the escape-set fixpoint ----------------------------------------------


ESCAPE_SRC = '''\
import threading


def helper():
    raise ValueError("boom")


def worker():
    helper()


def guarded():
    try:
        helper()
    except ValueError:
        pass


def reraiser():
    try:
        helper()
    except ValueError:
        raise


def parent_handler():
    try:
        raise ConnectionResetError("gone")
    except OSError:
        pass


def dyn(obj):
    obj.frobnicate()


def spawn():
    threading.Thread(target=worker, name="w", daemon=True).start()


class Widget:
    def frobnicate(self):
        return 1
'''


class TestEscapeFixpoint:
    MOD = "tpu_node_checker/escmod.py"

    def _esc(self, tmp_path, name):
        _, escapes = _escapes(_mini(tmp_path, {self.MOD: ESCAPE_SRC}))
        return set(escapes.get(f"{self.MOD}::{name}", frozenset()))

    def test_escape_propagates_through_callee(self, tmp_path):
        assert self._esc(tmp_path, "worker") == {"ValueError"}

    def test_handler_subtracts(self, tmp_path):
        assert self._esc(tmp_path, "guarded") == set()

    def test_bare_reraise_keeps_the_class(self, tmp_path):
        assert self._esc(tmp_path, "reraiser") == {"ValueError"}

    def test_parent_handler_covers_child(self, tmp_path):
        assert self._esc(tmp_path, "parent_handler") == set()

    def test_dynamic_dispatch_widens_to_exception(self, tmp_path):
        # .frobnicate() on an unknown receiver dispatch-falls-back onto
        # Widget.frobnicate — the fixpoint widens the call to Exception
        # rather than trusting any one candidate's summary.
        assert "Exception" in self._esc(tmp_path, "dyn")


# -- TNC114: the rule on top of the fixpoint -------------------------------


class TestExceptionEscapeRule:
    def test_doomed_thread_entry_flagged_at_def(self, tmp_path):
        root = _mini(tmp_path, {"tpu_node_checker/escmod.py": ESCAPE_SRC})
        assert _rule_findings(ExceptionEscape(), root) == [
            ("tpu_node_checker/escmod.py", 8)  # def worker
        ]

    def test_recording_worker_is_clean(self, tmp_path):
        src = (
            "import threading\n"
            "_DEATHS: list = []\n\n\n"
            "def worker():\n"
            "    try:\n"
            "        raise RuntimeError('x')\n"
            "    except Exception as exc:\n"
            "        _DEATHS.append(str(exc))\n\n\n"
            "def spawn():\n"
            "    threading.Thread(target=worker, name='w',\n"
            "                     daemon=True).start()\n"
        )
        root = _mini(tmp_path, {"tpu_node_checker/okmod.py": src})
        assert _rule_findings(ExceptionEscape(), root) == []

    def test_cli_main_may_only_raise_systemexit(self, tmp_path):
        bad = (
            "def main(argv=None):\n"
            "    raise ValueError('unhandled')\n"
        )
        root = _mini(tmp_path, {"tpu_node_checker/cli.py": bad})
        assert _rule_findings(ExceptionEscape(), root) == [
            ("tpu_node_checker/cli.py", 1)
        ]

    def test_cli_main_systemexit_is_sanctioned(self, tmp_path):
        ok = (
            "def main(argv=None):\n"
            "    raise SystemExit(2)\n"
        )
        root = _mini(tmp_path, {"tpu_node_checker/cli.py": ok})
        assert _rule_findings(ExceptionEscape(), root) == []


# -- TNC115/TNC117: the obligation interpreter -----------------------------


OBL_SRC = '''\
import socket


def leak():
    s = socket.socket()
    s.connect(("h", 1))


def branch_leak(flag):
    s = socket.socket()
    if flag:
        s.close()


def both_branches(flag):
    s = socket.socket()
    if flag:
        s.close()
    else:
        s.close()


def managed():
    with socket.socket() as s:
        s.connect(("h", 1))


def try_finally(flag):
    s = socket.socket()
    try:
        if flag:
            raise OSError("x")
    finally:
        s.close()


def may_raise():
    raise OSError("x")


def exc_path():
    s = socket.socket()
    may_raise()
    s.close()
'''

TRANSFER_SRC = '''\
import socket

_POOL: list = []


def minted():
    s = socket.socket()
    return s


class Box:
    def adopt(self):
        self.s = socket.socket()


def closer(conn):
    conn.close()


def handoff():
    s = socket.socket()
    closer(s)


def sunk():
    s = socket.socket()
    _POOL.append(s)


def laundered(harness):
    s = socket.socket()
    harness.launder(s)


def alias_close():
    s = socket.socket()
    t = s
    t.close()
'''

SKIP_SRC = '''\
def early(path, flag):
    fh = open(path, "rb")
    if flag:
        return None
    data = fh.read()
    fh.close()
    return data


def finally_closed(path, flag):
    fh = open(path, "rb")
    try:
        if flag:
            return None
        return fh.read()
    finally:
        fh.close()
'''


class TestMustRelease:
    def test_leaks_and_joins(self, tmp_path):
        root = _mini(tmp_path, {"tpu_node_checker/oblmod.py": OBL_SRC})
        assert _rule_findings(MustRelease(), root) == [
            ("tpu_node_checker/oblmod.py", 5),  # leak
            ("tpu_node_checker/oblmod.py", 10),  # branch_leak: join is OPEN
            ("tpu_node_checker/oblmod.py", 42),  # exc_path: raise skips close
        ]

    def test_exception_path_message_names_the_path(self, tmp_path):
        root = _mini(tmp_path, {"tpu_node_checker/oblmod.py": OBL_SRC})
        msgs = {
            f.line: f.message
            for f in MustRelease().check_project(load_project(root))
        }
        assert "exception path" in msgs[42]
        assert "normal path" in msgs[5]

    def test_transfer_matrix_is_all_clean(self, tmp_path):
        # return / store-into-self / releasing-callee / sink-method /
        # unknown-callee benefit-of-doubt / alias move: obligation leaves.
        root = _mini(tmp_path, {"tpu_node_checker/xfer.py": TRANSFER_SRC})
        assert _rule_findings(MustRelease(), root) == []


class TestFinallyHygiene:
    def test_early_return_reported_at_skip_site(self, tmp_path):
        root = _mini(tmp_path, {"tpu_node_checker/skipmod.py": SKIP_SRC})
        assert _rule_findings(FinallyHygiene(), root) == [
            ("tpu_node_checker/skipmod.py", 4)  # the `return None`
        ]
        # ...and TNC115 does NOT double-report the same obligation.
        assert _rule_findings(MustRelease(), root) == []


# -- TNC116: atomic writes in torn-tolerant store modules ------------------


STORE_SRC = '''\
import json
import os


def read_jsonl_tolerant(path):
    out = []
    try:
        with open(path, "rb") as fh:
            for line in fh:
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
    except OSError:
        return out
    return out


def torn(path, rows):
    with open(path, "w") as fh:
        for row in rows:
            fh.write(json.dumps(row) + "\\n")


def atomic(path, rows):
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        for row in rows:
            fh.write(json.dumps(row) + "\\n")
    os.replace(tmp, path)


def append_only(path, row):
    with open(path, "a") as fh:
        fh.write(json.dumps(row) + "\\n")


def load(path):  # the loader CALL is what marks this module a store
    return read_jsonl_tolerant(path)
'''

PLAIN_SRC = '''\
def overwrite(path, text):
    with open(path, "w") as fh:
        fh.write(text)
'''


class TestAtomicWrite:
    def test_torn_overwrite_flagged_in_store_module(self, tmp_path):
        root = _mini(tmp_path, {
            "tpu_node_checker/store.py": STORE_SRC,
            "tpu_node_checker/plain.py": PLAIN_SRC,
        })
        # Only the store module's torn write fires: the tmp+os.replace
        # shape and append mode are sanctioned, and plain.py (no
        # torn-tolerant loader in sight) is out of scope entirely.
        assert _rule_findings(AtomicWrite(), root) == [
            ("tpu_node_checker/store.py", 20)
        ]


# -- full vs incremental equivalence ---------------------------------------


class TestFullIncrementalEquivalence:
    FILES = {
        "tpu_node_checker/escmod.py": ESCAPE_SRC,
        "tpu_node_checker/oblmod.py": OBL_SRC,
        "tpu_node_checker/skipmod.py": SKIP_SRC,
        "tpu_node_checker/store.py": STORE_SRC,
    }

    @staticmethod
    def _typestate(report):
        return sorted(
            (f.code, f.path, f.line)
            for f in report.findings if f.code in TYPESTATE_CODES
        )

    def test_cold_warm_and_touched_runs_match_full(self, tmp_path):
        root = _mini(tmp_path, self.FILES)
        cache = str(tmp_path / "lint-cache.json")
        full = self._typestate(run_project(root))
        assert full  # all four rules have material in this checkout

        cold = run_incremental(root, cache_path=cache)
        assert self._typestate(cold) == full

        warm = run_incremental(root, cache_path=cache)
        assert self._typestate(warm) == full
        assert warm.cached_files > 0  # replayed, not re-scanned

        # Fix one leak; the slices must re-run enough to notice.
        mod = Path(root) / "tpu_node_checker" / "oblmod.py"
        mod.write_text(mod.read_text().replace(
            's.connect(("h", 1))\n\n\ndef branch_leak',
            's.connect(("h", 1))\n    s.close()\n\n\ndef branch_leak',
            1,
        ))
        after_full = self._typestate(run_project(root))
        after_inc = self._typestate(run_incremental(root, cache_path=cache))
        assert after_inc == after_full
        assert len(after_full) == len(full) - 1


# -- the SARIF surface -----------------------------------------------------


class TestSarif:
    def test_corpus_sarif_shape(self):
        report = run_project(str(CORPUS_ROOT))
        doc = json.loads(render_sarif(report))
        assert doc["version"] == SARIF_VERSION == "2.1.0"
        assert doc["$schema"].endswith("sarif-2.1.0.json")
        (run,) = doc["runs"]
        driver = run["tool"]["driver"]
        assert driver["name"] == "tnc-lint"
        rule_ids = {r["id"] for r in driver["rules"]}
        assert set(TYPESTATE_CODES) <= rule_ids

        results = run["results"]
        assert len(results) == len(report.findings) + len(report.suppressed)
        seen_codes = {r["ruleId"] for r in results}
        assert set(TYPESTATE_CODES) <= seen_codes
        for res in results:
            (loc,) = res["locations"]
            region = loc["physicalLocation"]["region"]
            assert region["startLine"] >= 1
            assert region["startColumn"] >= 1  # SARIF columns are 1-based

    def test_suppressed_findings_carry_in_source_status(self):
        report = run_project(str(CORPUS_ROOT))
        doc = json.loads(render_sarif(report))
        results = doc["runs"][0]["results"]
        suppressed = [r for r in results if r.get("suppressions")]
        assert len(suppressed) == len(report.suppressed)
        assert all(
            s["kind"] == "inSource"
            for r in suppressed for s in r["suppressions"]
        )


# -- suppression accounting (the stacked-waiver bugfix) --------------------


class TestStackedWaivers:
    def test_corpus_stacked_waivers_both_count_as_used(self):
        # lifecycle.sanctioned_probe carries a standalone waiver on the
        # line above AND a same-line waiver for the same rule.  Before
        # the (line, rule) multimap fix the standalone one shadowed the
        # same-line one in the lookup dict and was reported stale.
        report = run_project(str(CORPUS_ROOT))
        assert not [
            u for u in report.unused_suppressions
            if u["path"] == "tpu_node_checker/lifecycle.py"
        ]
        assert ("tpu_node_checker/lifecycle.py", "TNC115") in {
            (f.path, f.code) for f in report.suppressed
        }

    def test_mini_stacked_waivers(self, tmp_path):
        src = (
            "import socket\n\n\n"
            "def probe():\n"
            "    # tnc: allow-must-release(harness owns the fd)\n"
            "    s = socket.socket()  "
            "# tnc: allow-must-release(double account)\n"
            "    s.connect(('h', 1))\n"
        )
        root = _mini(tmp_path, {"tpu_node_checker/probe.py": src})
        report = run_project(root)
        assert not [f for f in report.findings if f.code == "TNC115"]
        assert [f for f in report.suppressed if f.code == "TNC115"]
        assert not [
            u for u in report.unused_suppressions
            if u["path"] == "tpu_node_checker/probe.py"
        ]
