"""Detection-core unit tests against the BASELINE.json fixture configs.

Covers the reference behavior contract (is_ready check-gpu-node.py:172-178,
capacity scan :181-196, node flattening :199-212, filtering :215-226) plus the
TPU-only additions: allocatable-over-capacity, topology labels, slice grouping.
"""

from tests import fixtures as fx
from tpu_node_checker.detect import (
    extract_node_info,
    group_slices,
    is_ready,
    parse_topology,
    select_accelerator_nodes,
    topology_chip_count,
)


class TestIsReady:
    def test_ready_true(self):
        assert is_ready(fx.make_node("n", ready=True))

    def test_ready_false(self):
        assert not is_ready(fx.make_node("n", ready=False))

    def test_missing_conditions(self):
        # Defensive defaults mirror check-gpu-node.py:173-178.
        assert not is_ready({"status": {}})
        assert not is_ready({})
        assert not is_ready({"status": {"conditions": [{"type": "Ready"}]}})

    def test_ready_unknown_status(self):
        node = fx.make_node("n", conditions=[{"type": "Ready", "status": "Unknown"}])
        assert not is_ready(node)


class TestExtractNodeInfo:
    def test_cpu_node_has_no_accelerators(self):
        info = extract_node_info(fx.cpu_only_cluster(1)[0])
        assert info.accelerators == 0
        assert info.breakdown == {}
        assert not info.is_tpu

    def test_gpu_node(self):
        info = extract_node_info(fx.gpu_pool(1)[0])
        assert info.accelerators == 1
        assert info.breakdown == {"nvidia.com/gpu": 1}
        assert info.families == ("gpu",)
        assert info.taints[0]["key"] == "nvidia.com/gpu"

    def test_tpu_node_topology_fields(self):
        info = extract_node_info(fx.tpu_v5e_single_host()[0])
        assert info.is_tpu
        assert info.accelerators == 8
        assert info.tpu_accelerator == "tpu-v5-lite-podslice"
        assert info.tpu_topology == "2x4"
        assert info.nodepool == "v5e-pool"

    def test_allocatable_preferred_over_capacity(self):
        # Node reserves 1 of 4 GPUs: allocatable must win (reference reads
        # capacity only — check-gpu-node.py:184-187 — and would report 4).
        node = fx.make_node(
            "n", allocatable={"nvidia.com/gpu": "3"}, capacity={"nvidia.com/gpu": "4"}
        )
        assert extract_node_info(node).accelerators == 3

    def test_capacity_fallback_when_allocatable_absent(self):
        node = {
            "metadata": {"name": "n"},
            "status": {"capacity": {"google.com/tpu": "4"}},
        }
        assert extract_node_info(node).accelerators == 4

    def test_to_dict_shape(self):
        d = extract_node_info(fx.tpu_v5e_single_host()[0]).to_dict()
        assert d["tpu"] == {
            "accelerator": "tpu-v5-lite-podslice",
            "topology": "2x4",
            "nodepool": "v5e-pool",
        }
        assert set(d) >= {"name", "ready", "accelerators", "breakdown", "labels", "taints"}


class TestSelect:
    def test_cpu_only_cluster_empty(self):
        accel, ready = select_accelerator_nodes(fx.cpu_only_cluster())
        assert accel == [] and ready == []

    def test_mixed_cluster_counts(self):
        accel, ready = select_accelerator_nodes(fx.mixed_cluster_one_notready())
        assert len(accel) == 4  # 2 GPU + 2 TPU; the CPU node is excluded
        assert len(ready) == 3  # one TPU host NotReady

    def test_all_notready_still_detected(self):
        accel, ready = select_accelerator_nodes(fx.gpu_pool(2, ready=False))
        assert len(accel) == 2 and ready == []

    def test_dead_device_plugin_visible_but_not_ready(self):
        # allocatable advertises zero TPUs while capacity shows 4 (device
        # plugin dead): the node must stay VISIBLE as an accelerator node
        # (else exit 3 would flip to exit 2) but must not count as Ready.
        node = fx.make_node(
            "sick-tpu",
            allocatable={"google.com/tpu": "0"},
            capacity={"cpu": "8", "google.com/tpu": "4"},
        )
        accel, ready = select_accelerator_nodes([node])
        assert len(accel) == 1
        assert accel[0].accelerators == 4
        assert accel[0].schedulable is False
        assert ready == []

    def test_fully_dead_gpu_plugin_rescued_by_gke_label(self):
        # GPU mirror of the TPU label rescue (VERDICT r01 item #4): device
        # plugin completely dead — no allocatable NOR capacity entry — but
        # the GKE GPU pool label identifies the hardware.  The node must stay
        # visible and unschedulable (exit 3 shape), not vanish (exit 2).
        node = fx.make_node(
            "sick-gpu",
            ready=True,
            allocatable={"cpu": "8"},
            capacity={"cpu": "8"},
            labels={"cloud.google.com/gke-accelerator": "nvidia-tesla-t4"},
        )
        accel, ready = select_accelerator_nodes([node])
        assert len(accel) == 1
        assert accel[0].families == ("gpu",)
        assert accel[0].accelerators == 0
        assert accel[0].schedulable is False
        assert ready == []

    def test_fully_dead_gpu_plugin_rescued_by_nvidia_present_label(self):
        # Same rescue via the NVIDIA GPU-operator / feature-discovery label.
        node = fx.make_node(
            "sick-gpu-gfd",
            ready=True,
            allocatable={"cpu": "8"},
            capacity={"cpu": "8"},
            labels={"nvidia.com/gpu.present": "true"},
        )
        accel, ready = select_accelerator_nodes([node])
        assert len(accel) == 1
        assert accel[0].families == ("gpu",)
        assert accel[0].schedulable is False
        assert ready == []

    def test_nvidia_present_false_is_not_rescued(self):
        # gpu.present="false" (or garbage) must NOT manufacture an
        # accelerator node out of a plain CPU host.
        node = fx.make_node(
            "plain-cpu",
            ready=True,
            allocatable={"cpu": "8"},
            capacity={"cpu": "8"},
            labels={"nvidia.com/gpu.present": "false"},
        )
        accel, ready = select_accelerator_nodes([node])
        assert accel == [] and ready == []

    def test_tpu_label_wins_over_gpu_label_on_dead_node(self):
        # Mixed labels (should not happen on GKE, but the wire is the wire):
        # the TPU identity takes precedence so slice grouping still sees it.
        node = fx.make_node(
            "weird",
            ready=True,
            allocatable={},
            capacity={},
            labels={
                "cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice",
                "cloud.google.com/gke-accelerator": "nvidia-tesla-t4",
            },
        )
        accel, _ = select_accelerator_nodes([node])
        assert accel[0].families == ("tpu",)
        assert accel[0].is_tpu


class TestTopology:
    def test_parse(self):
        assert parse_topology("2x4") == (2, 4)
        assert parse_topology("4x4x4") == (4, 4, 4)
        assert parse_topology("16x16") == (16, 16)

    def test_parse_garbage(self):
        assert parse_topology(None) is None
        assert parse_topology("") is None
        assert parse_topology("axb") is None
        assert parse_topology("0x4") is None

    def test_chip_count(self):
        assert topology_chip_count("4x4x4") == 64
        assert topology_chip_count("16x16") == 256


class TestSliceGrouping:
    def _slices(self, nodes):
        accel, _ = select_accelerator_nodes(nodes)
        return group_slices(accel)

    def test_v5p_64_one_slice(self):
        slices = self._slices(fx.tpu_v5p_64_slice())
        assert len(slices) == 1
        s = slices[0]
        assert len(s.hosts) == 16
        assert s.expected_hosts == 16
        assert s.chips == 64 and s.expected_chips == 64 and s.ready_chips == 64
        assert s.complete

    def test_v5p_one_host_down_incomplete(self):
        s = self._slices(fx.tpu_v5p_64_slice(not_ready=1))[0]
        assert len(s.ready_hosts) == 15
        assert s.ready_chips == 60
        assert not s.complete

    def test_v5e_256_north_star(self):
        s = self._slices(fx.tpu_v5e_256_slice())[0]
        assert len(s.hosts) == 64 and s.expected_hosts == 64
        assert s.chips == 256 and s.ready_chips == 256
        assert s.complete

    def test_missing_hosts_incomplete(self):
        # Only 60 of 64 node objects exist (hosts deleted/rescheduling):
        nodes = fx.tpu_v5e_256_slice()[:60]
        s = self._slices(nodes)[0]
        assert len(s.hosts) == 60 and s.expected_hosts == 64
        assert not s.complete

    def test_gpu_nodes_not_grouped(self):
        assert self._slices(fx.gpu_pool(2)) == []

    def test_mixed_cluster_slice(self):
        slices = self._slices(fx.mixed_cluster_one_notready())
        assert len(slices) == 1
        assert not slices[0].complete  # the NotReady host breaks the slice

    def test_two_distinct_pools_two_slices(self):
        nodes = fx.tpu_v5p_64_slice() + fx.tpu_v5e_single_host()
        assert len(self._slices(nodes)) == 2

    def test_single_host_no_labels_degenerate_slice(self):
        node = fx.make_node("bare-tpu", allocatable={"google.com/tpu": "4"})
        slices = self._slices([node])
        assert len(slices) == 1
        assert slices[0].complete  # single ready host, no topology claim

    def test_single_host_slice_pool_not_merged(self):
        # 8 independent single-host v5e nodes (topology 2x2 fits on one host)
        # in one nodepool, 7 of them NotReady: these are 8 slices, 7 degraded —
        # NOT one "complete" slice.
        nodes = [
            fx.make_node(
                f"gke-tpu-1h-{i}",
                ready=(i == 0),
                allocatable={"google.com/tpu": "4"},
                labels={
                    "cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-device",
                    "cloud.google.com/gke-tpu-topology": "2x2",
                    "cloud.google.com/gke-nodepool": "onehost-pool",
                },
            )
            for i in range(8)
        ]
        slices = self._slices(nodes)
        assert len(slices) == 8
        assert sum(1 for s in slices if s.complete) == 1
