"""Fleet analytics tier tests: segment store round-trip/compaction
property test, roll-up == raw-replay equivalence, bounded tail reads,
changepoint behavior, SLO queries, the checker wiring, and the served
endpoints.

Property-test style follows tests/test_history_store.py: seeded stdlib
``random``, no external fuzzing dependency.
"""

import json
import os
import random
import threading

import pytest

from tpu_node_checker import checker, cli
from tpu_node_checker.analytics import (
    CusumFlapDetector,
    SegmentStore,
    build_analytics_docs,
)
from tpu_node_checker.analytics.queries import replay_raw
from tpu_node_checker.analytics.segments import (
    RESOLUTIONS,
    ROLLUP_SCHEMA_VERSION,
    bucket_start,
)
from tpu_node_checker.history.fsm import HEALTHY, SUSPECT, HealthFSM
from tpu_node_checker.history.store import read_jsonl_tail

T0 = 1_700_000_000.0


@pytest.fixture(autouse=True)
def _fresh_caches():
    checker._ANALYTICS_CACHE["key"] = None
    checker._ANALYTICS_CACHE["bundle"] = None
    checker._HISTORY_CACHE["key"] = None
    checker._HISTORY_CACHE["tracker"] = None
    yield
    checker._ANALYTICS_CACHE["key"] = None
    checker._ANALYTICS_CACHE["bundle"] = None
    checker._HISTORY_CACHE["key"] = None
    checker._HISTORY_CACHE["tracker"] = None


def _write_history(path, rows):
    """rows: (node, ts, ok) triples → a --history JSONL file."""
    with open(path, "w", encoding="utf-8") as f:
        for node, ts, ok in rows:
            f.write(json.dumps({
                "schema": 1, "node": node, "ts": ts, "ok": ok,
                "causes": [], "state": "HEALTHY" if ok else "SUSPECT",
                "streak": 1, "flaps": 0, "flaps_total": 0,
            }) + "\n")


def _ingest(store, rows, flush_at=None):
    """Feed (node, ts, ok) rows through observe with oracle-equivalent
    flip computation, flushing at ``flush_at`` (default: after last ts +
    the coarsest resolution, closing every bucket)."""
    last_ok = {}
    last_ts = 0.0
    for node, ts, ok in rows:
        flipped = node in last_ok and last_ok[node] != ok
        last_ok[node] = ok
        last_ts = max(last_ts, ts)
        store.observe(node, ts, ok, "HEALTHY" if ok else "SUSPECT",
                      flipped, group={"cluster": "c0"})
    store.flush(flush_at if flush_at is not None
                else last_ts + RESOLUTIONS[-1] + 1)


def _stats_match(store, oracle):
    assert sorted(store.node_stats) == sorted(oracle)
    for node, want in oracle.items():
        got = store.node_stats[node]
        for key in ("n", "ok", "flips", "onsets", "repairs",
                    "first_ts", "last_ts", "last_ok"):
            assert got[key] == want[key], (node, key, got[key], want[key])
        assert round(got["repair_s"], 3) == want["repair_s"], node


# ---------------------------------------------------------------------------
# Bounded tail reads (the read_jsonl_tail satellite)
# ---------------------------------------------------------------------------


class TestReadJsonlTail:
    def test_tail_equals_full_read_suffix(self, tmp_path):
        p = tmp_path / "h.jsonl"
        p.write_text("".join(json.dumps({"i": i}) + "\n" for i in range(500)))
        entries, skipped, offset = read_jsonl_tail(str(p), max_lines=40)
        assert skipped == 0 and offset == p.stat().st_size
        assert [e["i"] for e in entries] == list(range(460, 500))

    def test_max_lines_larger_than_file_reads_everything(self, tmp_path):
        p = tmp_path / "h.jsonl"
        p.write_text(json.dumps({"a": 1}) + "\n")
        entries, _, _ = read_jsonl_tail(str(p), max_lines=10_000)
        assert entries == [{"a": 1}]

    def test_big_log_head_is_never_parsed(self, tmp_path):
        # The O(file)-RAM regression pin: a huge MALFORMED head would
        # inflate `skipped` if the loader touched it — a clean tail with
        # skipped == 0 proves only the tail was parsed.
        p = tmp_path / "big.jsonl"
        with open(p, "w") as f:
            for _ in range(200_000):
                f.write("not json " * 4 + "\n")
            for i in range(50):
                f.write(json.dumps({"i": i}) + "\n")
        entries, skipped, _ = read_jsonl_tail(str(p), max_lines=50)
        assert skipped == 0
        assert [e["i"] for e in entries] == list(range(50))

    def test_offset_resume_sees_only_appended_lines(self, tmp_path):
        p = tmp_path / "h.jsonl"
        p.write_text(json.dumps({"i": 0}) + "\n")
        _, _, offset = read_jsonl_tail(str(p))
        with open(p, "a") as f:
            f.write(json.dumps({"i": 1}) + "\n")
        entries, _, offset2 = read_jsonl_tail(str(p), start_offset=offset)
        assert [e["i"] for e in entries] == [1]
        # Nothing new: an empty read, offset stable.
        entries, _, offset3 = read_jsonl_tail(str(p), start_offset=offset2)
        assert entries == [] and offset3 == offset2

    def test_shrunk_file_is_reread_from_scratch(self, tmp_path):
        p = tmp_path / "h.jsonl"
        p.write_text("".join(json.dumps({"i": i}) + "\n" for i in range(9)))
        _, _, offset = read_jsonl_tail(str(p))
        p.write_text(json.dumps({"i": 99}) + "\n")  # compaction rewrite
        entries, _, _ = read_jsonl_tail(str(p), start_offset=offset)
        assert [e["i"] for e in entries] == [99]

    def test_partial_tail_consumed_by_default_like_tolerant_loader(
        self, tmp_path
    ):
        p = tmp_path / "h.jsonl"
        p.write_text(json.dumps({"a": 1}) + "\n" + '{"torn": tru')
        entries, skipped, _ = read_jsonl_tail(str(p))
        assert entries == [{"a": 1}] and skipped == 1

    def test_partial_tail_left_for_resume_when_asked(self, tmp_path):
        p = tmp_path / "h.jsonl"
        p.write_text(json.dumps({"a": 1}) + "\n" + '{"mid": 1')
        entries, skipped, offset = read_jsonl_tail(
            str(p), consume_partial_tail=False
        )
        assert entries == [{"a": 1}] and skipped == 0
        # The writer finishes the line: the resumed read sees it WHOLE.
        with open(p, "a") as f:
            f.write(', "write": 2}\n')
        entries, skipped, _ = read_jsonl_tail(str(p), start_offset=offset)
        assert entries == [{"mid": 1, "write": 2}] and skipped == 0

    def test_trend_output_byte_identical_under_the_tail_bound(
        self, tmp_path, capsys
    ):
        # The acceptance pin: --trend over the same log must not change
        # by a byte now that it reads through the bounded tail loader.
        log = tmp_path / "trend.jsonl"
        log.write_text("".join(
            json.dumps({"ts": T0 + 60 * i, "exit_code": 0 if i % 5 else 3,
                        "total_chips": 8, "ready_chips": 8 if i % 5 else 4})
            + "\n"
            for i in range(200)
        ))
        unbounded, _, _, _ = checker.compute_trend_summary(
            str(log), max_lines=10**9
        )
        bounded, _, _, _ = checker.compute_trend_summary(str(log))
        assert json.dumps(unbounded, sort_keys=True) == json.dumps(
            bounded, sort_keys=True
        )


# ---------------------------------------------------------------------------
# Segment store: round-trip, equivalence, compaction (seeded property)
# ---------------------------------------------------------------------------


class TestSegmentStore:
    def test_rollup_equals_raw_replay(self, tmp_path):
        rows = []
        rng = random.Random(1)
        for i in range(300):
            rows.append((f"n{i % 5}", T0 + 7 * i, rng.random() < 0.7))
        hist = tmp_path / "h.jsonl"
        _write_history(str(hist), rows)
        store = SegmentStore(str(tmp_path / "ana"))
        store.load()
        _ingest(store, rows)
        _stats_match(store, replay_raw(str(hist)))

    def test_closed_buckets_survive_restart(self, tmp_path):
        rows = [("n0", T0 + i, i % 3 != 0) for i in range(120)]
        store = SegmentStore(str(tmp_path / "ana"))
        store.load()
        _ingest(store, rows)
        assert store.rollup_lines_total > 0
        fresh = SegmentStore(str(tmp_path / "ana"))
        fresh.load()
        assert sorted(fresh.buckets) == sorted(store.buckets)
        for key, rec in store.buckets.items():
            got = fresh.buckets[key]
            for field in ("n", "ok", "flips", "onsets", "repairs",
                          "dwell", "last_ok", "cluster"):
                assert got.get(field) == rec.get(field), (key, field)

    def test_torn_final_segment_line_tolerated(self, tmp_path):
        rows = [("n0", T0 + i, True) for i in range(120)]
        store = SegmentStore(str(tmp_path / "ana"))
        store.load()
        _ingest(store, rows)
        shard = store.shard_of("n0")
        with open(store.segment_path(shard), "a") as f:
            f.write('{"node": "n0", "res": 60, "bucket"')  # crash mid-append
        fresh = SegmentStore(str(tmp_path / "ana"))
        fresh.load()
        assert fresh.skipped_lines == 1
        assert len(fresh.buckets) == len(store.buckets)

    def test_future_schema_lines_refused(self, tmp_path):
        store = SegmentStore(str(tmp_path / "ana"))
        store.load()
        _ingest(store, [("n0", T0 + i, True) for i in range(120)])
        shard = store.shard_of("n0")
        with open(store.segment_path(shard), "a") as f:
            f.write(json.dumps({
                "schema": ROLLUP_SCHEMA_VERSION + 1, "node": "n0",
                "res": 60, "bucket": int(T0) + 999_960, "n": 1, "ok": 1,
            }) + "\n")
        fresh = SegmentStore(str(tmp_path / "ana"))
        fresh.load()
        assert fresh.refused_lines == 1
        assert ("n0", 60, int(T0) + 999_960) not in fresh.buckets

    def test_sharding_matches_the_federation_ring(self, tmp_path):
        from tpu_node_checker.federation.endpoints import HashRing

        store = SegmentStore(str(tmp_path / "ana"), shards=8)
        ring = HashRing(range(8))
        for name in (f"gke-tpu-{i}" for i in range(50)):
            assert store.shard_of(name) == ring.assign(name)

    def test_seeded_property_compaction_and_crash(self, tmp_path,
                                                  monkeypatch):
        """1k random rounds: roll-up == raw-replay through restarts and
        compactions, a crash mid-compaction (injected rename failure)
        never corrupts the store, and compaction changes nothing
        observable."""
        rng = random.Random(0xA11A)
        for case in range(4):
            root = tmp_path / f"case{case}"
            nodes = [f"n{i}" for i in range(rng.randint(1, 6))]
            rows = []
            ts = T0
            for _ in range(1000 // max(1, len(nodes))):
                ts += rng.choice([1.0, 5.0, 30.0])
                for node in nodes:
                    if rng.random() < 0.8:
                        rows.append((node, ts, rng.random() < 0.6))
            hist = root.with_suffix(".jsonl")
            _write_history(str(hist), rows)
            store = SegmentStore(str(root))
            store.load()
            last_ok = {}
            for i, (node, row_ts, ok) in enumerate(rows):
                flipped = node in last_ok and last_ok[node] != ok
                last_ok[node] = ok
                store.observe(node, row_ts, ok,
                              "HEALTHY" if ok else "SUSPECT", flipped,
                              group={"cluster": "c0"})
                if i % 97 == 0:
                    store.flush(row_ts)
                if i % 211 == 0:
                    # Crash mid-compaction: the rename fails once; the
                    # store must stay readable and correct.
                    real_replace = os.replace

                    def _boom(src, dst):
                        raise OSError("injected crash")

                    monkeypatch.setattr(os, "replace", _boom)
                    for shard in range(store.shards):
                        store.compact_shard(shard)
                    monkeypatch.setattr(os, "replace", real_replace)
            store.flush(ts + RESOLUTIONS[-1] + 1)
            for shard in range(store.shards):
                store.compact_shard(shard)
            # In-session aggregates == the raw-replay oracle.
            _stats_match(store, replay_raw(str(hist)))
            # Compaction left no tmp droppings and a reloadable store.
            for shard in range(store.shards):
                assert not os.path.exists(
                    store.segment_path(shard) + ".tmp"
                )
            fresh = SegmentStore(str(root))
            fresh.load()
            assert fresh.skipped_lines == 0 and fresh.refused_lines == 0
            for key, rec in fresh.buckets.items():
                mine = store.buckets[key]
                assert rec.get("n") == mine.get("n"), key
                assert rec.get("flips") == mine.get("flips"), key

    def test_restart_refold_stitches_past_fine_retention(self, tmp_path):
        # 400 one-minute buckets — far past the 1m retention of 120.  The
        # refold must stitch the coarser resolutions underneath the fine
        # tail, so a restart keeps the FULL retained horizon instead of
        # collapsing the aggregates to ~2 hours.
        rng = random.Random(7)
        rows = [("n0", T0 + 60.0 * i, rng.random() < 0.8)
                for i in range(400)]
        hist = tmp_path / "h.jsonl"
        _write_history(str(hist), rows)
        store = SegmentStore(str(tmp_path / "ana"))
        store.load()
        _ingest(store, rows)
        fresh = SegmentStore(str(tmp_path / "ana"))
        fresh.load()
        _stats_match(fresh, replay_raw(str(hist)))

    def test_restart_mid_coarse_window_never_closes_partial(self, tmp_path):
        # The reviewer-verified scenario: 100 rounds land on disk only as
        # fine buckets (the open 6h accumulator dies with the process); a
        # restarted process observes 10 more rounds in the SAME 6h window
        # and flushes past it.  Without reconstruction the 6h bucket
        # closes with n=10 and the next refold collapses to ~9% of the
        # data; with it, every load sees all 110 rounds.
        rows_a = [("n0", T0 + 60.0 * i, True) for i in range(100)]
        store = SegmentStore(str(tmp_path / "ana"))
        store.load()
        _ingest(store, rows_a, flush_at=T0 + 60.0 * 100)  # 6h still open
        run2 = SegmentStore(str(tmp_path / "ana"))
        run2.load()  # restart: open accumulators were lost…
        assert run2.node_stats["n0"]["n"] == 100  # …but the refold stitches
        rows_b = [("n0", T0 + 60.0 * (100 + i), True) for i in range(10)]
        _ingest(run2, rows_b)  # flushes far past the window: 6h closes
        assert run2.node_stats["n0"]["n"] == 110
        run3 = SegmentStore(str(tmp_path / "ana"))
        run3.load()
        assert run3.node_stats["n0"]["n"] == 110

    def test_partial_coarse_bucket_on_disk_is_healed(self, tmp_path):
        # A 6h record that CLOSED partial (written by a pre-fix binary,
        # or a crash squeezing between reconstruction and compaction) is
        # replaced at load from the finer evidence and compacted durable.
        store = SegmentStore(str(tmp_path / "ana"))
        store.load()
        _ingest(store, [("n0", T0 + 60.0 * i, True) for i in range(30)])
        shard = store.shard_of("n0")
        window = bucket_start(T0, 21600)
        with open(store.segment_path(shard), "a") as f:
            f.write(json.dumps({
                "schema": ROLLUP_SCHEMA_VERSION, "node": "n0",
                "res": 21600, "bucket": window, "n": 3, "ok": 3,
                "flips": 0, "onsets": 0, "repairs": 0, "repair_s": 0.0,
                "dwell": {"HEALTHY": 3}, "first_ts": T0,
                "last_ts": T0 + 120.0, "last_ok": True,
            }) + "\n")
        fresh = SegmentStore(str(tmp_path / "ana"))
        fresh.load()
        assert fresh.buckets[("n0", 21600, window)]["n"] == 30
        assert fresh.node_stats["n0"]["n"] == 30
        # The heal is durable: a third load reads the compacted file.
        third = SegmentStore(str(tmp_path / "ana"))
        third.load()
        assert third.node_stats["n0"]["n"] == 30

    def test_restart_mid_failure_never_double_counts_onset(self, tmp_path):
        rows = [("n0", T0 + 60.0 * i, i < 3) for i in range(6)]  # fails at 3
        store = SegmentStore(str(tmp_path / "ana"))
        store.load()
        _ingest(store, rows)
        assert store.node_stats["n0"]["onsets"] == 1
        fresh = SegmentStore(str(tmp_path / "ana"))
        fresh.load()
        # Still failing across the restart: the repair clock is reseeded
        # (measured from the boundary), and the NEXT bad round must not
        # mint a second onset.
        fresh.observe("n0", T0 + 60.0 * 6, False, "FAILED", False)
        assert fresh.node_stats["n0"]["onsets"] == 1
        fresh.observe("n0", T0 + 60.0 * 7, True, "RECOVERING", True)
        assert fresh.node_stats["n0"]["repairs"] == 1

    def test_retention_bounds_buckets(self, tmp_path):
        from tpu_node_checker.analytics.segments import RETENTION_BUCKETS

        store = SegmentStore(str(tmp_path / "ana"))
        store.load()
        # 400 one-minute buckets: far past the 1m retention of 120.
        rows = [("n0", T0 + 60.0 * i, True) for i in range(400)]
        _ingest(store, rows)
        per_res = {}
        for (_n, res, _b) in store.buckets:
            per_res[res] = per_res.get(res, 0) + 1
        for res, n in per_res.items():
            assert n <= RETENTION_BUCKETS[res], (res, n)


# ---------------------------------------------------------------------------
# Changepoint detector
# ---------------------------------------------------------------------------


class TestCusumFlapDetector:
    def _drive(self, det, node, verdicts):
        fired = []
        for i, ok in enumerate(verdicts):
            flipped = det.flip(node, ok)
            if det.observe(node, flipped, i):
                fired.append(i)
        return fired

    def test_steady_node_never_fires(self):
        det = CusumFlapDetector()
        assert self._drive(det, "n", [True] * 50) == []
        assert self._drive(det, "m", [False] * 50) == []

    def test_single_transient_never_fires(self):
        det = CusumFlapDetector()
        assert self._drive(det, "n", [True, True, False, True, True]) == []

    def test_two_separated_incidents_never_fire(self):
        det = CusumFlapDetector()
        verdicts = [True, False, True, True, True, True, False, True, True]
        assert self._drive(det, "n", verdicts) == []

    def test_flapper_fires_on_third_flip_once_per_episode(self):
        det = CusumFlapDetector()
        verdicts = [True, False, True, False, True, False, True]
        assert self._drive(det, "n", verdicts) == [3]  # flips at 1,2,3
        assert det.detections_total == 1
        assert det.active == {"n": 3}

    def test_episode_rearms_after_decay(self):
        det = CusumFlapDetector()
        fired = self._drive(
            det, "n",
            [True, False, True, False, True]  # fires at i=3
            + [True] * 4                      # decays: episode closes
            + [False, True, False, True],     # flaps again: refires
        )
        assert len(fired) == 2 and det.detections_total == 2

    def test_promotion_only_from_healthy_and_never_accelerates(self):
        fsm = HealthFSM(cordon_after=3)
        fsm.observe("n", True)
        assert fsm.promote_suspect("n") == (HEALTHY, SUSPECT)
        assert fsm.health("n").state == SUSPECT
        assert fsm.health("n").streak == 0
        # Already SUSPECT: a second promotion is a no-op.
        assert fsm.promote_suspect("n") is None
        # The promoted node still needs the FULL K consecutive bad rounds.
        fsm.observe("n", False)
        fsm.observe("n", False)
        assert fsm.health("n").state == SUSPECT
        fsm.observe("n", False)
        assert fsm.health("n").state == "FAILED"

    def test_promotion_unknown_node_is_noop(self):
        fsm = HealthFSM()
        assert fsm.promote_suspect("ghost") is None
        assert "ghost" not in fsm.nodes

    def test_prune_closes_a_departed_nodes_episode(self):
        det = CusumFlapDetector()
        self._drive(det, "gone", [True, False, True, False, True])
        assert "gone" in det.active
        det.prune({"still-here"})
        assert det.active == {} and det.active_count() == 0
        assert det.score("gone") == 0.0

    def test_departed_node_leaves_the_standing_suspect_set(self, tmp_path):
        nodes_file = tmp_path / "nodes.json"
        args = cli.parse_args([
            "--nodes-json", str(nodes_file),
            "--history", str(tmp_path / "h.jsonl"),
            "--analytics", str(tmp_path / "ana"),
            "--json",
        ])
        for r in range(5):  # flap until detection
            nodes_file.write_text(json.dumps(
                {"items": [_node("flappy", ready=(r % 2 == 0)),
                           _node("steady")]}
            ))
            res = checker.run_check(args)
        assert res.payload["analytics"]["suspects"] == ["flappy"]
        # The flapper is deleted from the cluster: the next round's
        # standing set must not carry the ghost forever.
        nodes_file.write_text(json.dumps({"items": [_node("steady")]}))
        res = checker.run_check(args)
        assert res.payload["analytics"]["suspects"] == []


# ---------------------------------------------------------------------------
# Queries
# ---------------------------------------------------------------------------


class TestQueries:
    def _store(self, tmp_path):
        store = SegmentStore(str(tmp_path / "ana"))
        store.load()
        rows = []
        for i in range(200):
            rows.append(("good", T0 + 30 * i, True))
            rows.append(("bad", T0 + 30 * i, i % 4 != 0))
        _ingest(store, rows)
        store.node_groups["good"] = {"cluster": "c0", "slice": "s0"}
        store.node_groups["bad"] = {"cluster": "c0", "slice": "s1"}
        return store

    def test_docs_shape_and_grouping(self, tmp_path):
        docs = build_analytics_docs(self._store(tmp_path))
        slo = docs["slo"]
        assert slo["fleet"]["nodes"] == 2
        assert slo["source"] == "rollups"
        kinds = {(g["kind"], g["group"]) for g in slo["groups"]}
        assert ("cluster", "c0") in kinds
        assert ("slice", "s0") in kinds and ("slice", "s1") in kinds
        cluster = next(g for g in slo["groups"]
                       if (g["kind"], g["group"]) == ("cluster", "c0"))
        assert cluster["nodes"] == 2
        assert cluster["availability_pct"]["p50"] is not None

    def test_offenders_rank_worst_first(self, tmp_path):
        docs = build_analytics_docs(self._store(tmp_path))
        names = [o["node"] for o in docs["offenders"]["offenders"]]
        assert names == ["bad", "good"]
        assert docs["offenders"]["nodes_total"] == 2

    def test_flaps_doc_carries_detector_state(self, tmp_path):
        det = CusumFlapDetector()
        for i in range(5):
            ok = i % 2 == 0
            det.observe("bad", det.flip("bad", ok), i)
        docs = build_analytics_docs(self._store(tmp_path), detector=det,
                                    predictions=[{"node": "bad"}])
        flaps = docs["flaps"]
        bad = next(n for n in flaps["nodes"] if n["node"] == "bad")
        assert bad["predicted"] is True and bad["cusum"] is not None
        assert bad["recent_buckets"], "closed 1m buckets expected"
        assert flaps["predictions"] == [{"node": "bad"}]
        assert flaps["predictions_total"] == 1


# ---------------------------------------------------------------------------
# Checker wiring + served endpoints
# ---------------------------------------------------------------------------


def _node(name, ready=True):
    return {
        "metadata": {"name": name, "labels": {
            "cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice",
            "cloud.google.com/gke-tpu-topology": "4x4",
            "cloud.google.com/gke-nodepool": "pool-0",
        }},
        "spec": {},
        "status": {
            "conditions": [
                {"type": "Ready", "status": "True" if ready else "False"}
            ],
            "allocatable": {"google.com/tpu": "4"},
        },
    }


class TestCheckerWiring:
    def _args(self, tmp_path, nodes_file):
        return cli.parse_args([
            "--nodes-json", str(nodes_file),
            "--history", str(tmp_path / "h.jsonl"),
            "--analytics", str(tmp_path / "ana"),
            "--json", "--cluster-name", "c0",
        ])

    def _run_flapping(self, tmp_path, rounds=6):
        nodes_file = tmp_path / "nodes.json"
        results = []
        args = self._args(tmp_path, nodes_file)
        for r in range(rounds):
            doc = {"items": [_node("flappy", ready=(r % 2 == 0)),
                             _node("steady")]}
            nodes_file.write_text(json.dumps(doc))
            results.append(checker.run_check(args))
        return results

    def test_detection_promotes_and_surfaces(self, tmp_path):
        results = self._run_flapping(tmp_path)
        detected = [
            (i, p)
            for i, res in enumerate(results)
            for p in res.payload["analytics"]["predictions"]
        ]
        assert detected, "flapping node never detected"
        round_i, pred = detected[0]
        assert pred["node"] == "flappy" and round_i == 3
        # Standing episode rides every later payload.
        assert results[-1].payload["analytics"]["suspects"] == ["flappy"]
        # The steady node never contributes a prediction.
        assert all(p["node"] == "flappy" for _, p in detected)

    def test_docs_built_and_payload_block_stable_fields(self, tmp_path):
        res = self._run_flapping(tmp_path)[-1]
        assert set(res.analytics_docs) == {"slo", "offenders", "flaps"}
        block = res.payload["analytics"]
        assert set(block) == {
            "predictions", "predictions_total", "suspects", "buckets",
            "rollup_lines_total", "compactions_total", "sketch_samples",
        }

    def test_no_flag_payload_untouched(self, tmp_path):
        nodes_file = tmp_path / "nodes.json"
        nodes_file.write_text(json.dumps({"items": [_node("n0")]}))
        args = cli.parse_args([
            "--nodes-json", str(nodes_file),
            "--history", str(tmp_path / "h.jsonl"), "--json",
        ])
        res = checker.run_check(args)
        assert "analytics" not in res.payload
        assert res.analytics_docs is None

    def test_metrics_families_emitted(self, tmp_path):
        from tpu_node_checker.metrics import render_metrics

        res = self._run_flapping(tmp_path)[-1]
        text = render_metrics(res)
        for family in (
            "tpu_node_checker_analytics_predictions_total",
            "tpu_node_checker_analytics_suspects",
            "tpu_node_checker_analytics_rollup_lines_total",
            "tpu_node_checker_analytics_compactions_total",
        ):
            assert f"# TYPE {family}" in text, family
        # Explicit --cluster-name labels every round family.
        assert 'tpu_node_checker_analytics_suspects{cluster="c0"} 1' in text

    def test_prediction_feeds_budget_view(self, tmp_path):
        nodes_file = tmp_path / "nodes.json"
        reports = tmp_path / "probes"
        reports.mkdir()
        args = cli.parse_args([
            "--nodes-json", str(nodes_file),
            "--history", str(tmp_path / "h.jsonl"),
            "--analytics", str(tmp_path / "ana"),
            "--probe-results", str(reports),
            "--cordon-failed", "--cordon-dry-run",
            "--cordon-after", "3",
            "--disruption-budget", "2",
            "--json", "--cluster-name", "c0",
        ])
        import time as _time

        checker._REMEDIATION_CACHE["key"] = None
        checker._REMEDIATION_CACHE["bundle"] = None
        res = None
        for r in range(6):
            ok = r % 2 == 0
            nodes_file.write_text(json.dumps(
                {"items": [_node("flappy"), _node("steady")]}
            ))
            for name, verdict in (("flappy", ok), ("steady", True)):
                (reports / f"{name}.json").write_text(json.dumps({
                    "ok": verdict, "level": "compute", "hostname": name,
                    "written_at": _time.time(),
                }))
            res = checker.run_check(args)
        prediction = res.payload["remediation"]["prediction"]
        assert prediction["suspects"] == ["flappy"]
        assert prediction["domains"] == ["pool-0/tpu-v5-lite-podslice/4x4"]

    def test_served_endpoints(self, tmp_path):
        import http.client

        from tpu_node_checker.server.app import FleetStateServer

        res = self._run_flapping(tmp_path)[-1]
        srv = FleetStateServer(0, host="127.0.0.1")
        try:
            srv.publish(res)
            srv.publish_analytics(res.analytics_docs)

            def get(path):
                conn = http.client.HTTPConnection(
                    "127.0.0.1", srv.port, timeout=10
                )
                try:
                    conn.request("GET", path)
                    r = conn.getresponse()
                    return r.status, r.read()
                finally:
                    conn.close()

            for key in ("slo", "offenders", "flaps"):
                status, body = get(f"/api/v1/analytics/{key}")
                assert status == 200, key
                json.loads(body)
            status, body = get("/api/v1/analytics/flaps")
            doc = json.loads(body)
            assert any(n["node"] == "flappy" for n in doc["nodes"])
            # Clearing swaps back to the helpful 404.
            srv.publish_analytics(None)
            status, body = get("/api/v1/analytics/slo")
            assert status == 404 and b"--analytics" in body
        finally:
            srv.close()

    def test_endpoint_reads_race_free_under_swaps(self, tmp_path):
        """16 readers across live publish_analytics swaps: every response
        is a complete, parseable document (the TNC011 atomic-swap rule
        applied to the analytics entities)."""
        import http.client

        from tpu_node_checker.server.app import FleetStateServer

        res = self._run_flapping(tmp_path)[-1]
        srv = FleetStateServer(0, host="127.0.0.1")
        try:
            srv.publish(res)
            srv.publish_analytics(res.analytics_docs)
            stop = threading.Event()
            errors = []

            def hammer():
                conn = http.client.HTTPConnection(
                    "127.0.0.1", srv.port, timeout=10
                )
                try:
                    while not stop.is_set():
                        conn.request("GET", "/api/v1/analytics/slo")
                        r = conn.getresponse()
                        body = r.read()
                        if r.status != 200:
                            errors.append(r.status)
                        else:
                            json.loads(body)
                except Exception as exc:  # pragma: no cover - surfaced below
                    errors.append(repr(exc))
                finally:
                    conn.close()

            threads = [
                threading.Thread(target=hammer, name=f"tnc-ana-hammer-{i}",
                                 daemon=True)
                for i in range(8)
            ]
            for t in threads:
                t.start()
            for _ in range(25):
                srv.publish_analytics(res.analytics_docs)
            stop.set()
            for t in threads:
                t.join(timeout=10)
            assert errors == []
        finally:
            srv.close()


class TestCliValidation:
    def test_analytics_requires_history(self):
        with pytest.raises(SystemExit):
            cli.parse_args(["--analytics", "d"])

    def test_analytics_accepted_with_watch_stream(self):
        # PR 19 lifted this rejection: roll-up folding rides the tick
        # path itself, so stream rounds produce the same buckets poll
        # rounds do (steady ticks included).
        args = cli.parse_args(["--watch", "5", "--watch-stream",
                               "--history", "h", "--analytics", "d"])
        assert args.watch_stream and args.analytics == "d"

    def test_analytics_rejected_with_emit_probe(self):
        with pytest.raises(SystemExit):
            cli.parse_args(["--emit-probe", "out.json",
                            "--history", "h", "--analytics", "d"])

    def test_analytics_rejected_standalone_serve(self):
        with pytest.raises(SystemExit):
            cli.parse_args(["--serve", "0", "--history", "h",
                            "--analytics", "d"])

    def test_analytics_accepted_with_watch_serve(self):
        args = cli.parse_args(["--watch", "5", "--serve", "0",
                               "--history", "h", "--analytics", "d"])
        assert args.analytics == "d"


# ---------------------------------------------------------------------------
# Federated analytics: merged sketches vs the raw-replay oracle (PR 19)
# ---------------------------------------------------------------------------


class TestGlobalAnalyticsMerge:
    """The acceptance pin: global p50/p90/p99 availability/MTBF/MTTR
    computed from MERGED per-cluster sketches equal a raw-replay oracle
    over the union of per-node stats, within the declared alpha bound —
    the analytics flavor of PR 15's roll-up == replay pin."""

    def _cluster_store(self, tmp_path, name, nodes, seed):
        from tpu_node_checker.analytics.segments import SegmentStore

        rng = random.Random(seed)
        store = SegmentStore(str(tmp_path / name))
        store.load()
        rows = []
        for i in range(nodes):
            node = f"{name}-n{i}"
            fail_rate = rng.uniform(0.02, 0.4)
            for r in range(120):
                rows.append((node, T0 + 30 * r, rng.random() > fail_rate))
        rows.sort(key=lambda row: row[1])
        _ingest(store, rows)
        for i in range(nodes):
            store.node_groups[f"{name}-n{i}"] = {"cluster": name}
        return store

    @staticmethod
    def _oracle_pct(values, q):
        import math

        ordered = sorted(values)
        rank = max(1, math.ceil(q * len(ordered)))
        return ordered[rank - 1]

    def test_global_quantiles_match_union_oracle(self, tmp_path):
        from tpu_node_checker.analytics.queries import (
            build_analytics_docs,
            node_stats_view,
        )
        from tpu_node_checker.analytics.sketch import DEFAULT_ALPHA
        from tpu_node_checker.federation.merge import (
            ClusterView,
            build_global_analytics,
        )

        views = []
        union = {"availability_pct": [], "mtbf_s": [], "mttr_s": []}
        for idx, name in enumerate(("us-a", "eu-b", "ap-c")):
            store = self._cluster_store(tmp_path, name, nodes=12, seed=idx)
            # Oracle side: the raw per-node values, no sketches involved.
            for stats in node_stats_view(store).values():
                for metric in union:
                    if stats[metric] is not None:
                        union[metric].append(stats[metric])
            view = ClusterView(name, f"http://{name}:8080")
            view.set_analytics(build_analytics_docs(store)["slo"])
            views.append(view)

        doc = build_global_analytics(views)
        assert doc["source"] == "sketches"
        assert set(doc["clusters"]) == {"us-a", "eu-b", "ap-c"}
        assert doc["fleet"]["nodes"] == 36
        for metric, values in union.items():
            assert values, metric
            got = doc["fleet"][metric]
            for q, key in ((0.5, "p50"), (0.9, "p90"), (0.99, "p99")):
                exact = self._oracle_pct(values, q)
                est = got[key]
                assert abs(est - exact) <= DEFAULT_ALPHA * exact + 1e-9, (
                    metric, key, est, exact)

    def test_cluster_groups_synthesized_and_offenders_reranked(self, tmp_path):
        from tpu_node_checker.analytics.queries import build_analytics_docs
        from tpu_node_checker.federation.merge import (
            ClusterView,
            build_global_analytics,
        )

        views = []
        for idx, name in enumerate(("us-a", "eu-b")):
            store = self._cluster_store(tmp_path, name, nodes=6, seed=10 + idx)
            view = ClusterView(name, f"http://{name}:8080")
            view.set_analytics(build_analytics_docs(store)["slo"])
            views.append(view)
        doc = build_global_analytics(views)
        kinds = {(g["kind"], g["group"]) for g in doc["groups"]}
        assert ("cluster", "us-a") in kinds and ("cluster", "eu-b") in kinds
        # Offenders: union of both clusters' worst, cluster-stamped,
        # worst availability first.
        assert doc["offenders"], "offenders expected from flapping fixtures"
        avails = [o["availability_pct"] for o in doc["offenders"]]
        assert avails == sorted(avails)
        assert {o["cluster"] for o in doc["offenders"]} <= {"us-a", "eu-b"}

    def test_restacks_through_an_aggregator_tier(self, tmp_path):
        """Tier stacking: merging {A,B} then {that, C} equals merging
        {A,B,C} flat — build_global_analytics consumes its own output."""
        from tpu_node_checker.analytics.queries import build_analytics_docs
        from tpu_node_checker.federation.merge import (
            ClusterView,
            build_global_analytics,
        )

        def _view(name, doc):
            v = ClusterView(name, f"http://{name}:8080")
            v.set_analytics(doc)
            return v

        slos = {
            name: build_analytics_docs(
                self._cluster_store(tmp_path, name, nodes=8, seed=20 + i)
            )["slo"]
            for i, name in enumerate(("us-a", "eu-b", "ap-c"))
        }
        flat = build_global_analytics(
            [_view(n, d) for n, d in slos.items()])
        lower = build_global_analytics(
            [_view(n, slos[n]) for n in ("us-a", "eu-b")])
        stacked = build_global_analytics(
            [_view("agg-west", lower), _view("ap-c", slos["ap-c"])])
        assert stacked["fleet"]["nodes"] == flat["fleet"]["nodes"] == 24
        for metric in ("availability_pct", "mtbf_s", "mttr_s"):
            assert stacked["fleet"][metric] == flat["fleet"][metric], metric

    def test_no_analytics_views_yield_none(self):
        from tpu_node_checker.federation.merge import (
            ClusterView,
            build_global_analytics,
        )

        view = ClusterView("us-a", "http://us-a:8080")
        assert build_global_analytics([view]) is None
