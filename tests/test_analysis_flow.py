"""The whole-program flow tier: call-graph builder units, thread-entry
inference, the TNC111/112/113 graph rules, root-level suppression
accounting, the ``--graph json`` dump, and the ``--changed-only``
incremental cache.

Graph units build miniature checkouts under tmp_path — the builder only
needs a ``tpu_node_checker/`` directory — and assert on the resolved
edges and the explicit ``unresolved`` bucket: every soundness gap must be
COUNTED, so the bucket is asserted non-zero wherever dynamism is seeded
(a silently-empty bucket would mean the builder started lying).
"""

import json
from pathlib import Path

import pytest

from tpu_node_checker.analysis.engine import load_project, run_project
from tpu_node_checker.analysis.flow.entries import (
    compute_domains,
    infer_entries,
)
from tpu_node_checker.analysis.flow.graph import build_graph

CORPUS_ROOT = Path(__file__).resolve().parent / "analysis_fixtures" / "repo"


def _mini(tmp_path, files):
    """Write a miniature checkout; returns its root as str."""
    for rel, source in files.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(source)
    (tmp_path / "tpu_node_checker").mkdir(exist_ok=True)
    init = tmp_path / "tpu_node_checker" / "__init__.py"
    if not init.exists():
        init.write_text("")
    return str(tmp_path)


def _graph(tmp_path, files):
    return build_graph(load_project(_mini(tmp_path, files)))


def _edges(graph):
    return {(s.caller, t) for s in graph.calls for t in s.targets}


class TestCallGraphBuilder:
    def test_direct_and_imported_calls_resolve(self, tmp_path):
        graph = _graph(tmp_path, {
            "tpu_node_checker/a.py": (
                "from tpu_node_checker.b import helper\n"
                "import tpu_node_checker.b as bee\n"
                "def caller():\n"
                "    helper()\n"
                "    bee.helper()\n"
            ),
            "tpu_node_checker/b.py": "def helper():\n    return 1\n",
        })
        caller = "tpu_node_checker/a.py::caller"
        helper = "tpu_node_checker/b.py::helper"
        assert (caller, helper) in _edges(graph)
        kinds = [s.kind for s in graph.calls if s.caller == caller]
        assert kinds.count("direct") == 2  # both spellings resolve

    def test_self_method_dispatch_and_base_class(self, tmp_path):
        graph = _graph(tmp_path, {
            "tpu_node_checker/c.py": (
                "class Base:\n"
                "    def shared(self):\n"
                "        return 1\n"
                "class Child(Base):\n"
                "    def go(self):\n"
                "        self.shared()\n"
                "        self.local()\n"
                "    def local(self):\n"
                "        return 2\n"
            ),
        })
        go = "tpu_node_checker/c.py::Child.go"
        assert (go, "tpu_node_checker/c.py::Base.shared") in _edges(graph)
        assert (go, "tpu_node_checker/c.py::Child.local") in _edges(graph)

    def test_constructor_assignment_types_locals(self, tmp_path):
        graph = _graph(tmp_path, {
            "tpu_node_checker/d.py": (
                "class Store:\n"
                "    def get(self):\n"
                "        return 1\n"
                "def use():\n"
                "    store = Store()\n"
                "    return store.get()\n"
            ),
        })
        assert ("tpu_node_checker/d.py::use",
                "tpu_node_checker/d.py::Store.get") in _edges(graph)

    def test_decorator_unwrapping_and_property(self, tmp_path):
        graph = _graph(tmp_path, {
            "tpu_node_checker/e.py": (
                "import functools\n"
                "def deco(fn):\n"
                "    return fn\n"
                "@deco\n"
                "def wrapped():\n"
                "    return 1\n"
                "class Box:\n"
                "    @property\n"
                "    def value(self):\n"
                "        return 1\n"
                "def use():\n"
                "    return wrapped()\n"
            ),
        })
        # The decorated function is registered under its own name and
        # calls to it resolve to the body that executes.
        assert ("tpu_node_checker/e.py::use",
                "tpu_node_checker/e.py::wrapped") in _edges(graph)
        box = graph.classes["tpu_node_checker/e.py::Box"]
        assert "value" in box.properties

    def test_dynamic_dispatch_fallback_low_fanout(self, tmp_path):
        graph = _graph(tmp_path, {
            "tpu_node_checker/f.py": (
                "class OnlyOne:\n"
                "    def peculiar_method(self):\n"
                "        return 1\n"
                "def use(thing):\n"
                "    return thing.peculiar_method()\n"
            ),
        })
        (site,) = [s for s in graph.calls
                   if s.name == "thing.peculiar_method"]
        assert site.kind == "fallback"
        assert site.targets == (
            "tpu_node_checker/f.py::OnlyOne.peculiar_method",)

    def test_dispatch_past_the_fanout_cap_is_unresolved(self, tmp_path):
        classes = "\n".join(
            f"class C{i}:\n    def crowded(self):\n        return {i}"
            for i in range(5)
        )
        graph = _graph(tmp_path, {
            "tpu_node_checker/g.py": (
                f"{classes}\n"
                "def use(thing):\n"
                "    return thing.crowded()\n"
            ),
        })
        (site,) = [s for s in graph.calls if s.name == "thing.crowded"]
        assert site.kind == "unresolved"
        assert graph.counts["unresolved"] >= 1

    def test_unresolved_bucket_counted_never_silently_zero(self, tmp_path):
        graph = _graph(tmp_path, {
            "tpu_node_checker/h.py": (
                "def use(callback, registry):\n"
                "    callback()\n"
                "    registry['x']()\n"
            ),
        })
        assert graph.counts["unresolved"] == 2
        assert len(graph.unresolved) == 2
        # The four buckets partition every recorded call.
        assert sum(graph.counts.values()) == len(graph.calls)

    def test_repo_graph_buckets_partition_and_count_gaps(self):
        # The real corpus carries seeded dynamism (params called as
        # functions) — the bucket must be non-zero there, proving the
        # builder counts what it cannot see instead of dropping it.
        graph = build_graph(load_project(str(CORPUS_ROOT)))
        assert sum(graph.counts.values()) == len(graph.calls)
        assert graph.counts["resolved"] > 0
        doc = graph.to_dict()
        assert doc["counts"] == graph.counts
        assert len(doc["unresolved"]) == graph.counts["unresolved"]


class TestThreadEntries:
    def test_thread_target_partial_and_lambda(self, tmp_path):
        graph = _graph(tmp_path, {
            "tpu_node_checker/t.py": (
                "import threading\n"
                "from functools import partial\n"
                "def loop():\n"
                "    return 1\n"
                "def other(x):\n"
                "    return x\n"
                "def spawn():\n"
                "    threading.Thread(target=loop, name='a', daemon=True).start()\n"
                "    threading.Thread(target=partial(other, 1), name='b', daemon=True).start()\n"
                "    threading.Thread(target=lambda: loop(), name='c', daemon=True).start()\n"
            ),
        })
        entries = infer_entries(graph)
        fids = {e.fid for e in entries}
        assert "tpu_node_checker/t.py::loop" in fids
        assert "tpu_node_checker/t.py::other" in fids
        assert any("<lambda>" in fid for fid in fids)

    def test_thread_subclass_signal_and_router_handlers(self, tmp_path):
        graph = _graph(tmp_path, {
            "tpu_node_checker/u.py": (
                "import signal\n"
                "import threading\n"
                "class Reader(threading.Thread):\n"
                "    def run(self):\n"
                "        return 1\n"
                "def on_term(signum, frame):\n"
                "    return None\n"
                "def handle_get(req):\n"
                "    return req\n"
                "def wire(router):\n"
                "    signal.signal(signal.SIGTERM, on_term)\n"
                "    router.add('GET', '/x', handle_get)\n"
            ),
        })
        entries = infer_entries(graph)
        kinds = {e.fid: e.kind for e in entries}
        assert kinds["tpu_node_checker/u.py::Reader.run"] == "thread-subclass"
        assert kinds["tpu_node_checker/u.py::on_term"] == "signal"
        assert kinds["tpu_node_checker/u.py::handle_get"] == "http-handler"

    def test_parameter_spawner_roots_call_site_argument(self, tmp_path):
        graph = _graph(tmp_path, {
            "tpu_node_checker/v.py": (
                "def bounded_map(fn, items, pool):\n"
                "    return [pool.submit(fn, item) for item in items]\n"
                "def worker(item):\n"
                "    return item\n"
                "def caller(pool):\n"
                "    bounded_map(worker, [1], pool)\n"
            ),
        })
        entries = infer_entries(graph)
        spawned = {e.fid: e.kind for e in entries}
        assert spawned.get("tpu_node_checker/v.py::worker") == "spawner-arg"

    def test_domains_span_thread_and_main(self, tmp_path):
        graph = _graph(tmp_path, {
            "tpu_node_checker/w.py": (
                "import threading\n"
                "def shared():\n"
                "    return 1\n"
                "def loop():\n"
                "    shared()\n"
                "def spawn():\n"
                "    threading.Thread(target=loop, name='x', daemon=True).start()\n"
                "def sync_path():\n"
                "    shared()\n"
            ),
        })
        domains = compute_domains(graph, infer_entries(graph))
        shared = domains["tpu_node_checker/w.py::shared"]
        assert len(shared) >= 2  # the worker thread AND main both reach it
        assert "main" in shared


_READ_ROOT_FILE = "tpu_node_checker/server/workers.py"


def _tnc111_project(sleep_in_callee: bool, suppress_root: bool = False):
    waiver = ("    # tnc: allow-transitive-blocking(unit: sanctioned root)\n"
              if suppress_root else "")
    callee_body = ("    time.sleep(0.1)\n" if sleep_in_callee
                   else "    pass\n")
    return {
        _READ_ROOT_FILE: (
            "from tpu_node_checker.helper import do_fetch\n"
            "class W:\n"
            f"{waiver}"
            "    def _get_thing(self, key):\n"
            "        return do_fetch(key)\n"
        ),
        "tpu_node_checker/helper.py": (
            "import time\n"
            "def do_fetch(key):\n"
            f"{callee_body}"
            "    return key\n"
        ),
    }


class TestTransitiveBlocking:
    def test_cross_file_blocking_lands_on_root(self, tmp_path):
        root = _mini(tmp_path, _tnc111_project(sleep_in_callee=True))
        report = run_project(root, only_rules=["transitive-blocking"])
        (finding,) = report.findings
        assert finding.code == "TNC111"
        assert finding.path == _READ_ROOT_FILE
        assert "time.sleep" in finding.message
        assert "helper.py" in finding.message  # names the real site

    def test_clean_callee_chain_is_quiet(self, tmp_path):
        root = _mini(tmp_path, _tnc111_project(sleep_in_callee=False))
        report = run_project(root, only_rules=["transitive-blocking"])
        assert report.findings == []

    def test_root_suppression_covers_callee_file_blocking(self, tmp_path):
        root = _mini(tmp_path, _tnc111_project(True, suppress_root=True))
        report = run_project(root, only_rules=["transitive-blocking"])
        assert report.findings == []
        (shushed,) = report.suppressed
        assert shushed.code == "TNC111"
        assert report.unused_suppressions == []

    def test_suppression_surfaces_unused_when_path_disappears(self, tmp_path):
        # The waiver stays on the root, the blocking callee goes away:
        # the engine must report the orphaned waiver, not silently keep it.
        root = _mini(tmp_path, _tnc111_project(False, suppress_root=True))
        report = run_project(root, only_rules=["transitive-blocking"])
        assert report.findings == []
        (unused,) = report.unused_suppressions
        assert unused["rule"] == "transitive-blocking"
        assert unused["path"] == _READ_ROOT_FILE


class TestLocksetRace:
    def _project(self, spawn: bool = True, lock_in_helper: bool = False):
        helper_write = (
            "    with state._lock:\n        state.count = 0\n"
            if lock_in_helper else "    state.count = 0\n"
        )
        spawn_src = (
            "import threading\n"
            "from tpu_node_checker.race_helper import reset\n"
            "from tpu_node_checker.race_state import State\n"
            "def start(state: 'State'):\n"
            "    threading.Thread(target=_loop, args=(state,),"
            " name='x', daemon=True).start()\n"
            "def _loop(state: 'State'):\n"
            "    reset(state)\n"
        ) if spawn else (
            "from tpu_node_checker.race_helper import reset\n"
            "from tpu_node_checker.race_state import State\n"
            "def sync_only(state: 'State'):\n"
            "    reset(state)\n"
        )
        return {
            "tpu_node_checker/race_state.py": (
                "import threading\n"
                "class State:\n"
                "    def __init__(self):\n"
                "        self._lock = threading.Lock()\n"
                "        self.count = 0\n"
                "    def bump(self):\n"
                "        with self._lock:\n"
                "            self.count += 1\n"
            ),
            "tpu_node_checker/race_helper.py": (
                "from tpu_node_checker.race_state import State\n"
                "def reset(state: 'State'):\n"
                f"{helper_write}"
            ),
            "tpu_node_checker/race_spawn.py": spawn_src,
        }

    def test_cross_file_unguarded_write_fires(self, tmp_path):
        root = _mini(tmp_path, self._project())
        report = run_project(root, only_rules=["lockset-race"])
        (finding,) = report.findings
        assert finding.code == "TNC112"
        assert finding.path == "tpu_node_checker/race_helper.py"
        assert "State.count" in finding.message

    def test_locked_helper_is_quiet(self, tmp_path):
        root = _mini(tmp_path, self._project(lock_in_helper=True))
        report = run_project(root, only_rules=["lockset-race"])
        assert report.findings == []

    def test_single_domain_is_quiet(self, tmp_path):
        root = _mini(tmp_path, self._project(spawn=False))
        report = run_project(root, only_rules=["lockset-race"])
        assert report.findings == []

    def test_inherited_lockset_rescues_helper(self, tmp_path):
        # The helper never takes the lock lexically, but its ONLY caller
        # holds it — the call-graph meet must rescue the site.
        files = self._project()
        files["tpu_node_checker/race_state.py"] = (
            "import threading\n"
            "from tpu_node_checker.race_inner import bump_inner\n"
            "class State:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.count = 0\n"
            "    def bump(self):\n"
            "        with self._lock:\n"
            "            bump_inner(self)\n"
        )
        files["tpu_node_checker/race_inner.py"] = (
            "from tpu_node_checker.race_state import State\n"
            "def bump_inner(state: 'State'):\n"
            "    state.count += 1\n"
        )
        root = _mini(tmp_path, files)
        report = run_project(root, only_rules=["lockset-race"])
        # race_helper's bare write still fires; the inherited-lock site
        # in race_inner must NOT.
        assert [f.path for f in report.findings] == [
            "tpu_node_checker/race_helper.py"
        ]

    def test_sanctioned_snapshot_swap_attr_is_excused(self, tmp_path):
        files = self._project()
        files["tpu_node_checker/race_state.py"] = (
            "import threading\n"
            "class State:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._snap = None\n"
            "    def publish(self, snap):\n"
            "        with self._lock:\n"
            "            self._snap = snap\n"
        )
        files["tpu_node_checker/race_helper.py"] = (
            "from tpu_node_checker.race_state import State\n"
            "def reset(state: 'State'):\n"
            "    state._snap = None\n"
        )
        root = _mini(tmp_path, files)
        report = run_project(root, only_rules=["lockset-race"])
        assert report.findings == []  # SANCTIONED_LOCKFREE: atomic swap


class TestSnapshotEscape:
    def test_corpus_seeds_cover_every_escape_shape(self):
        report = run_project(str(CORPUS_ROOT),
                             only_rules=["snapshot-escape"])
        lines = {(f.path, f.line) for f in report.findings
                 if f.code == "TNC113"}  # engine meta findings still run
        source = (CORPUS_ROOT / "tpu_node_checker" / "server"
                  / "escape.py").read_text().splitlines()
        expected = {
            ("tpu_node_checker/server/escape.py", i + 1)
            for i, line in enumerate(source) if "EXPECT[TNC113]" in line
        }
        assert lines == expected
        # Four distinct escape shapes seeded: store/feed/return/callee.
        assert len(expected) == 4

    def test_feed_mutation_outside_server_dir_fires(self, tmp_path):
        # TNC102 never looks outside server/ — the dataflow rule must.
        root = _mini(tmp_path, {
            "tpu_node_checker/pub.py": (
                "class P:\n"
                "    def __init__(self):\n"
                "        self._snap = None\n"
                "    def publish(self, payload):\n"
                "        entities = dict(payload)\n"
                "        snap = {'entities': entities}\n"
                "        self._snap = snap\n"
                "        entities['late'] = 1\n"
            ),
        })
        report = run_project(root, only_rules=["snapshot-escape"])
        (finding,) = report.findings
        assert finding.code == "TNC113"
        assert "'entities'" in finding.message


class TestGraphDumpCli:
    def test_graph_json_document(self, capsys):
        from tpu_node_checker.analysis.__main__ import EXIT_CLEAN, main

        rc = main(["--root", str(CORPUS_ROOT), "--graph", "json"])
        assert rc == EXIT_CLEAN
        doc = json.loads(capsys.readouterr().out)
        assert set(doc) >= {"modules", "functions", "classes", "edges",
                            "counts", "unresolved", "thread_entries",
                            "multi_domain_functions", "build_ms"}
        assert doc["counts"]["resolved"] > 0
        # The corpus spawns a worker thread (flowpkg/spawn.py).
        assert any(e["kind"] == "thread" for e in doc["thread_entries"])


class TestIncrementalCache:
    def _run(self, root, cache):
        from tpu_node_checker.analysis.cache import run_incremental

        return run_incremental(str(root), cache_path=str(cache))

    def _key(self, report):
        return (
            [f.to_dict() for f in report.findings],
            [f.to_dict() for f in report.suppressed],
            report.unused_suppressions,
            report.files_scanned,
        )

    @pytest.fixture()
    def corpus_copy(self, tmp_path):
        import shutil

        dst = tmp_path / "repo"
        shutil.copytree(CORPUS_ROOT, dst,
                        ignore=shutil.ignore_patterns("__pycache__"))
        return dst

    def test_cold_then_warm_matches_full_run(self, corpus_copy, tmp_path):
        cache = tmp_path / "cache.json"
        full = run_project(str(corpus_copy))
        cold = self._run(corpus_copy, cache)
        warm = self._run(corpus_copy, cache)
        assert self._key(cold) == self._key(full)
        assert self._key(warm) == self._key(full)
        assert cold.cached_files == 0
        assert warm.cached_files > 0

    def test_changed_file_relints_and_matches_full(self, corpus_copy,
                                                   tmp_path):
        cache = tmp_path / "cache.json"
        self._run(corpus_copy, cache)
        target = corpus_copy / "tpu_node_checker" / "defaults.py"
        target.write_text(target.read_text()
                          + "\ndef fresh(x=[]):\n    return x\n")
        inc = self._run(corpus_copy, cache)
        full = run_project(str(corpus_copy))
        assert self._key(inc) == self._key(full)
        assert any(f.line > 1 and f.path.endswith("defaults.py")
                   for f in inc.findings)

    def test_graph_rule_replayed_until_slice_changes(self, corpus_copy,
                                                     tmp_path):
        cache = tmp_path / "cache.json"
        self._run(corpus_copy, cache)
        # README-only change: contracts re-run, graph rules replay.
        readme = corpus_copy / "README.md"
        readme.write_text(readme.read_text() + "\nextra line\n")
        inc = self._run(corpus_copy, cache)
        assert "TNC203" in inc.timings_ms
        assert "TNC111" not in inc.timings_ms
        # Package change inside TNC111's slice: the rule re-runs.
        storeio = corpus_copy / "tpu_node_checker" / "storeio.py"
        storeio.write_text(storeio.read_text() + "\n# moved\n")
        inc2 = self._run(corpus_copy, cache)
        assert "TNC111" in inc2.timings_ms
        full = run_project(str(corpus_copy))
        assert self._key(inc2) == self._key(full)

    def test_graph_suppression_unused_after_path_disappears(self, tmp_path):
        import shutil

        src = _mini(tmp_path / "proj",
                    _tnc111_project(True, suppress_root=True))
        cache = tmp_path / "cache.json"
        first = self._run(Path(src), cache)
        assert first.findings == [] and len(first.suppressed) == 1
        # The blocking path disappears; the waiver must surface as
        # unused THROUGH the incremental path too.
        for rel, content in _tnc111_project(False,
                                            suppress_root=True).items():
            (Path(src) / rel).write_text(content)
        second = self._run(Path(src), cache)
        assert any(u["rule"] == "transitive-blocking"
                   for u in second.unused_suppressions)
        shutil.rmtree(src, ignore_errors=True)

    def test_analyzer_source_change_invalidates_everything(
            self, corpus_copy, tmp_path, monkeypatch):
        # Editing a rule's LOGIC moves no code/slug, but the cached
        # verdicts were produced by the old semantics — the fingerprint
        # hashes the installed analyzer's sources, so every entry drops.
        from tpu_node_checker.analysis import cache as cache_mod

        cache = tmp_path / "cache.json"
        self._run(corpus_copy, cache)
        warm = self._run(corpus_copy, cache)
        assert warm.cached_files > 0
        monkeypatch.setattr(cache_mod, "_analysis_sources_sha",
                            lambda: "rule-logic-changed")
        invalidated = self._run(corpus_copy, cache)
        assert invalidated.cached_files == 0
        assert self._key(invalidated) == self._key(warm)

    def test_corrupt_cache_degrades_to_full_run(self, corpus_copy,
                                                tmp_path):
        cache = tmp_path / "cache.json"
        cache.write_text("{not json")
        report = self._run(corpus_copy, cache)
        full = run_project(str(corpus_copy))
        assert self._key(report) == self._key(full)

    def test_rule_filter_rejects_changed_only(self, capsys):
        from tpu_node_checker.analysis.__main__ import EXIT_USAGE, main

        rc = main(["--root", str(CORPUS_ROOT), "--changed-only",
                   "--rule", "mutable-default"])
        assert rc == EXIT_USAGE
        assert "bypasses" in capsys.readouterr().err


class TestTimingsSurface:
    def test_json_report_carries_timings(self, capsys):
        from tpu_node_checker.analysis.__main__ import main

        main(["--root", str(CORPUS_ROOT), "--format", "json"])
        doc = json.loads(capsys.readouterr().out)
        t = doc["timings_ms"]
        assert "parse" in t and "total" in t and "graph_build" in t
        assert "typestate_build" in t
        for code in ("TNC111", "TNC112", "TNC113",
                     "TNC114", "TNC115", "TNC116", "TNC117"):
            assert code in t
        assert doc["schema"] == 3

    def test_human_output_has_timing_line(self, capsys):
        from tpu_node_checker.analysis.__main__ import main

        main(["--root", str(CORPUS_ROOT)])
        out = capsys.readouterr().out
        assert "tnc-lint timings: total" in out
