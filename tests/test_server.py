"""Fleet state API server (``--serve``): snapshot swap, negotiation, auth.

The serving contract under test:

* every GET is answered from an IMMUTABLE pre-serialized snapshot — the
  hammer test polls all endpoints from 16 threads while rounds swap
  snapshots underneath and asserts zero torn/invalid JSON, zero 500s, and
  ETags that are stable within a round and different across rounds;
* writes are deny-by-default (no token → 403, bad token → 401) and
  evidence-gated (FSM rules → 409), with the live PATCH observed
  server-side exactly once;
* ``/api/v1/trend`` is cached — rebuilt on publication or file change,
  never per request;
* without ``--serve`` nothing changes: payload bytes and metrics output
  are identical whether the flag surface exists or not.

Wall-clock guard (same policy as tests/test_retry.py): nothing here sleeps
for real — waits are event-based or bounded socket I/O — and every test is
timed; a leaked sleep or a wedged handler fails the suite, not just slows it.
"""

import gzip
import http.client
import json
import os
import threading
import time

import pytest

from tests import fixtures as fx
from tpu_node_checker import checker, cli
from tpu_node_checker.server import app as server_app
from tpu_node_checker.server.app import FleetStateServer
from tpu_node_checker.server.auth import check_write_auth, resolve_serve_token
from tpu_node_checker.server.router import Response, Router, negotiate
from tpu_node_checker.server.snapshot import (
    Entity,
    build_snapshot,
    build_snapshot_delta,
    build_store_snapshot,
)

WALL_CLOCK_BUDGET_S = 20.0


@pytest.fixture(autouse=True)
def _wall_clock_guard():
    t0 = time.perf_counter()
    yield
    elapsed = time.perf_counter() - t0
    assert elapsed < WALL_CLOCK_BUDGET_S, (
        f"server test burned {elapsed:.1f}s of wall-clock — a real sleep or "
        "a wedged handler leaked in"
    )


def _result(nodes=None, extra=()):
    args = cli.parse_args(["--json", *extra])
    return checker.run_check(
        args,
        nodes=[json.loads(json.dumps(n)) for n in (nodes or fx.tpu_v5e_256_slice())],
    )


def _req(port, method, path, headers=None, body=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    try:
        conn.request(method, path, body=body, headers=headers or {})
        resp = conn.getresponse()
        return resp.status, dict(resp.headers.items()), resp.read()
    finally:
        conn.close()


@pytest.fixture
def server():
    srv = FleetStateServer(0, host="127.0.0.1")
    yield srv
    srv.close()


# ---------------------------------------------------------------------------
# Router + negotiation units
# ---------------------------------------------------------------------------


class TestRouter:
    def _router(self):
        r = Router()
        r.add("GET", "/api/v1/nodes", lambda req: Response(200, b"list"))
        r.add("GET", "/api/v1/nodes/{name}", lambda req: Response(200, b"one"))
        r.add("POST", "/api/v1/nodes/{name}/cordon", lambda req: Response(200))
        return r

    def test_param_capture_and_percent_decoding(self):
        handler, params, pattern = self._router().resolve(
            "GET", "/api/v1/nodes/gke-tpu%2F0"
        )
        assert params == {"name": "gke-tpu/0"}
        assert pattern == "/api/v1/nodes/{name}"

    def test_unknown_path_is_404(self):
        resp = self._router().resolve("GET", "/api/v2/nodes")
        assert isinstance(resp, Response) and resp.status == 404

    def test_wrong_method_is_405_with_allow(self):
        resp = self._router().resolve("DELETE", "/api/v1/nodes")
        assert isinstance(resp, Response) and resp.status == 405
        assert resp.headers["Allow"] == "GET, HEAD"

    def test_head_resolves_through_get(self):
        handler, params, pattern = self._router().resolve("HEAD", "/api/v1/nodes")
        assert pattern == "/api/v1/nodes"


class TestNegotiate:
    def test_strong_etag_304(self):
        entity = Entity(b"x" * 400)
        hit = negotiate(entity, {"If-None-Match": entity.etag})
        assert hit.status == 304 and hit.body == b""
        assert hit.headers["ETag"] == entity.etag

    @pytest.mark.parametrize(
        "header",
        ['"nope", {etag}', "W/{etag}", "*"],
    )
    def test_etag_list_weak_and_star_forms(self, header):
        entity = Entity(b"y" * 400)
        got = negotiate(entity, {"If-None-Match": header.format(etag=entity.etag)})
        assert got.status == 304

    def test_miss_serves_body_with_etag(self):
        entity = Entity(b"z" * 400)
        got = negotiate(entity, {"If-None-Match": '"something-else"'})
        assert got.status == 200 and got.body == entity.raw
        assert got.headers["Vary"] == "Accept-Encoding"

    def test_gzip_only_when_accepted_and_smaller(self):
        big = Entity(json.dumps({"k": ["v"] * 200}).encode())
        plain = negotiate(big, {})
        assert plain.body == big.raw and "Content-Encoding" not in plain.headers
        gz = negotiate(big, {"Accept-Encoding": "gzip, br"})
        assert gz.headers["Content-Encoding"] == "gzip"
        assert gzip.decompress(gz.body) == big.raw
        # Tiny bodies skip gzip entirely (the header would cost more).
        small = Entity(b"{}")
        assert small.gz is None
        got = negotiate(small, {"Accept-Encoding": "gzip"})
        assert got.body == small.raw and "Content-Encoding" not in got.headers


class TestAuth:
    def test_no_token_configured_is_403_final(self):
        status, reason = check_write_auth(None, "Bearer anything")
        assert status == 403 and "disabled" in reason

    def test_missing_or_malformed_header_is_401(self):
        assert check_write_auth("s3cret", None)[0] == 401
        assert check_write_auth("s3cret", "Basic s3cret")[0] == 401

    def test_wrong_token_is_401_right_token_passes(self):
        assert check_write_auth("s3cret", "Bearer wrong")[0] == 401
        assert check_write_auth("s3cret", "Bearer s3cret") == (None, "")

    def test_env_fallback_flag_wins(self, monkeypatch):
        monkeypatch.setenv("TNC_SERVE_TOKEN", "from-env")
        assert resolve_serve_token(None) == "from-env"
        assert resolve_serve_token("from-flag") == "from-flag"
        monkeypatch.delenv("TNC_SERVE_TOKEN")
        assert resolve_serve_token(None) is None


# ---------------------------------------------------------------------------
# Read surface
# ---------------------------------------------------------------------------


class TestReadSurface:
    def test_endpoints_serve_the_published_round(self, server):
        result = _result()
        server.publish(result)
        port = server.port

        status, headers, body = _req(port, "GET", "/api/v1/summary")
        summary = json.loads(body)
        assert status == 200
        assert summary["round"] == 1
        assert summary["exit_code"] == 0
        assert summary["total_nodes"] == result.payload["total_nodes"]
        assert summary["ready_chips"] == 256
        assert summary["slices"] == {"total": 1, "complete": 1}

        status, _, body = _req(port, "GET", "/api/v1/nodes")
        nodes = json.loads(body)
        assert status == 200 and nodes["count"] == 64
        # Verbatim payload entries — the API must not re-derive the round.
        assert nodes["nodes"] == result.payload["nodes"]

        name = result.payload["nodes"][0]["name"]
        status, _, body = _req(port, "GET", f"/api/v1/nodes/{name}")
        assert status == 200 and json.loads(body)["node"]["name"] == name

        status, _, body = _req(port, "GET", "/api/v1/slices")
        slices = json.loads(body)
        assert status == 200 and slices["slices"] == result.payload["slices"]

    def test_unknown_node_404s_with_round(self, server):
        server.publish(_result())
        status, _, body = _req(server.port, "GET", "/api/v1/nodes/nope")
        assert status == 404 and json.loads(body)["round"] == 1

    def test_unknown_path_404_and_wrong_method_405(self, server):
        server.publish(_result())
        assert _req(server.port, "GET", "/api/v2/summary")[0] == 404
        status, headers, _ = _req(server.port, "POST", "/api/v1/summary")
        assert status == 405 and "GET" in headers["Allow"]

    def test_head_matches_get_headers_with_no_body(self, server):
        server.publish(_result())
        g_status, g_headers, g_body = _req(server.port, "GET", "/api/v1/nodes")
        h_status, h_headers, h_body = _req(server.port, "HEAD", "/api/v1/nodes")
        assert (h_status, h_body) == (200, b"")
        assert h_headers["Content-Length"] == str(len(g_body))
        assert h_headers["ETag"] == g_headers["ETag"]

    def test_etag_hit_304_and_gzip_roundtrip(self, server):
        server.publish(_result())
        _, headers, body = _req(server.port, "GET", "/api/v1/nodes")
        etag = headers["ETag"]
        status, headers2, body2 = _req(
            server.port, "GET", "/api/v1/nodes", {"If-None-Match": etag}
        )
        assert (status, body2) == (304, b"") and headers2["ETag"] == etag
        status, headers3, body3 = _req(
            server.port, "GET", "/api/v1/nodes", {"Accept-Encoding": "gzip"}
        )
        assert headers3.get("Content-Encoding") == "gzip"
        assert gzip.decompress(body3) == body
        assert len(body3) < len(body)

    def test_503_before_first_round(self, server):
        for path in ("/api/v1/summary", "/api/v1/nodes", "/api/v1/slices",
                     "/api/v1/nodes/x"):
            status, _, body = _req(server.port, "GET", path)
            assert status == 503, path
            assert "no completed" in json.loads(body)["error"]

    def test_trend_404_when_not_configured(self, server):
        server.publish(_result())
        assert _req(server.port, "GET", "/api/v1/trend")[0] == 404

    def test_unread_post_body_does_not_desync_keepalive(self, server):
        # A 404/405 answer must still drain the request body: leftover
        # bytes in the socket would be parsed as the START of the next
        # keep-alive request on the connection.
        server.publish(_result())
        conn = http.client.HTTPConnection("127.0.0.1", server.port, timeout=10)
        try:
            conn.request(
                "POST", "/api/v1/unknown", body=b'{"x": "y"}',
                headers={"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            resp.read()
            assert resp.status == 404
            # Same connection: the next request must parse cleanly.
            conn.request("GET", "/healthz")
            resp = conn.getresponse()
            resp.read()
            assert resp.status == 200
        finally:
            conn.close()

    def test_metrics_carries_fleet_and_server_families(self, server):
        server.publish(_result())
        _req(server.port, "GET", "/api/v1/summary")
        _, _, body = _req(server.port, "GET", "/metrics")
        text = body.decode()
        assert 'tpu_node_checker_chips{state="ready"} 256' in text
        assert 'tpu_node_checker_api_server_requests_total{method="GET"' in text
        assert "tpu_node_checker_api_server_in_flight" in text
        assert "tpu_node_checker_api_server_auth_failures_total 0" in text


class TestReadiness:
    def test_healthz_always_ok_readyz_needs_a_round(self, server):
        assert _req(server.port, "GET", "/healthz")[0] == 200
        status, _, body = _req(server.port, "GET", "/readyz")
        assert status == 503 and json.loads(body)["ready"] is False
        server.publish(_result())
        status, _, body = _req(server.port, "GET", "/readyz")
        doc = json.loads(body)
        assert status == 200 and doc["ready"] is True and doc["round"] == 1

    def test_open_breaker_flips_readyz_snapshot_keeps_serving(self, server):
        server.publish(_result(), breaker={"open": False, "consecutive_failures": 0})
        assert _req(server.port, "GET", "/readyz")[0] == 200
        server.mark_error({"open": True, "consecutive_failures": 3})
        status, _, body = _req(server.port, "GET", "/readyz")
        assert status == 503
        assert "breaker open" in json.loads(body)["reason"]
        # The stale-but-present snapshot still answers reads.
        assert _req(server.port, "GET", "/api/v1/summary")[0] == 200
        # Recovery: the next published round restores readiness.
        server.publish(_result(), breaker={"open": False, "consecutive_failures": 0})
        assert _req(server.port, "GET", "/readyz")[0] == 200


# ---------------------------------------------------------------------------
# Delta-patched snapshots (watch-stream incremental publishes)
# ---------------------------------------------------------------------------


class TestDeltaSnapshots:
    def _two_rounds(self):
        nodes = fx.tpu_v5p_64_slice()[:8]
        r1 = _result(nodes)
        sick = [json.loads(json.dumps(n)) for n in nodes]
        sick[3]["status"]["conditions"][1]["status"] = "False"
        r2 = _result(sick)
        return nodes, r1, r2

    def test_delta_body_is_byte_identical_to_full_rebuild(self):
        nodes, r1, r2 = self._two_rounds()
        changed_name = nodes[3]["metadata"]["name"]
        prev = build_snapshot(r1.payload, r1.exit_code, 1, 100.0)
        full = build_snapshot(r2.payload, r2.exit_code, 2, 200.0)
        delta = build_snapshot_delta(
            prev, r2.payload, r2.exit_code, 2, 200.0, {changed_name}
        )
        for key in ("summary", "nodes", "slices"):
            assert delta.entities[key].raw == full.entities[key].raw
            assert delta.entities[key].etag == full.entities[key].etag

    def test_unchanged_entries_are_reference_reused(self):
        nodes, r1, r2 = self._two_rounds()
        changed_name = nodes[3]["metadata"]["name"]
        prev = build_snapshot(r1.payload, r1.exit_code, 1, 100.0)
        delta = build_snapshot_delta(
            prev, r2.payload, r2.exit_code, 2, 200.0, {changed_name}
        )
        for n in nodes:
            name = n["metadata"]["name"]
            if name == changed_name:
                assert delta.node_entities[name] is not prev.node_entities[name]
                assert delta.node_entities[name].etag != prev.node_entities[name].etag
            else:
                # Object identity, not mere equality: zero re-encode work,
                # and the poller's cached per-node ETag keeps 304-ing.
                assert delta.node_entities[name] is prev.node_entities[name]
                assert delta.node_fragments[name] is prev.node_fragments[name]
                assert delta.node_docs[name] is prev.node_docs[name]

    def test_empty_delta_preserves_node_bytes(self):
        nodes, r1, _ = self._two_rounds()
        prev = build_snapshot(r1.payload, r1.exit_code, 1, 100.0)
        delta = build_snapshot_delta(prev, r1.payload, r1.exit_code, 2, 200.0, set())
        # Per-node representations are bit-for-bit the previous round's;
        # only the round-stamped collection heads move.
        assert delta.node_entities == prev.node_entities
        assert delta.node_fragments == prev.node_fragments

    def test_node_absent_from_prev_is_encoded_fresh(self):
        nodes, r1, r2 = self._two_rounds()
        prev = build_snapshot(r1.payload, r1.exit_code, 1, 100.0)
        # Simulate a node that flickered out of the previous snapshot: the
        # delta builder must fall back to a fresh encode, never KeyError or
        # serve a stale entry.
        victim = nodes[5]["metadata"]["name"]
        del prev.node_fragments[victim]
        del prev.node_entities[victim]
        del prev.node_docs[victim]
        full = build_snapshot(r1.payload, r1.exit_code, 2, 200.0)
        delta = build_snapshot_delta(
            prev, r1.payload, r1.exit_code, 2, 200.0, set()
        )
        assert delta.entities["nodes"].raw == full.entities["nodes"].raw
        assert delta.node_entities[victim].raw == full.node_entities[victim].raw

    def test_publish_with_changed_set_serves_the_delta(self, server):
        nodes, r1, r2 = self._two_rounds()
        changed_name = nodes[3]["metadata"]["name"]
        unchanged_name = nodes[0]["metadata"]["name"]
        server.publish(r1)
        status, headers, _ = _req(server.port, "GET", f"/api/v1/nodes/{unchanged_name}")
        assert status == 200
        etag_before = headers["ETag"]
        collection_etag = _req(server.port, "GET", "/api/v1/nodes")[1]["ETag"]
        server.publish(r2, changed=frozenset({changed_name}))
        # The unchanged node's representation (and ETag) is carried over:
        # a poller re-sending it stays on the 304 diet.
        status, headers, _ = _req(
            server.port, "GET", f"/api/v1/nodes/{unchanged_name}",
            headers={"If-None-Match": etag_before},
        )
        assert status == 304
        # The changed node and the collection moved.
        status, _, body = _req(server.port, "GET", f"/api/v1/nodes/{changed_name}")
        assert status == 200
        assert json.loads(body)["node"]["ready"] is False
        assert _req(server.port, "GET", "/api/v1/nodes")[1]["ETag"] != collection_etag

    def test_hammer_across_incremental_swaps(self, server):
        nodes, r1, r2 = self._two_rounds()
        changed = frozenset({nodes[3]["metadata"]["name"]})
        server.publish(r1)
        paths = (
            "/api/v1/summary", "/api/v1/nodes",
            "/api/v1/nodes/" + nodes[0]["metadata"]["name"],
            "/api/v1/nodes/" + nodes[3]["metadata"]["name"],
        )

        def swaps():
            # 25 live incremental swaps, alternating the sick/healthy
            # rounds, every one a delta publish against the snapshot in
            # service.
            for i in range(25):
                server.publish(r2 if i % 2 == 0 else r1, changed=changed)

        flat = fx.hammer_fleet_api(
            server.port, paths, swaps, thread_prefix="tnc-test-delta-hammer"
        )
        # Per-node entities keep their round stamp across delta publishes
        # by design (a node's round is its last-modified round), so the
        # bijection here is the 200/304 + parses contract only.
        fx.assert_poll_contract(flat, bijection=False)


# ---------------------------------------------------------------------------
# The hammer: concurrent polls across live snapshot swaps
# ---------------------------------------------------------------------------


class TestHammer:
    ENDPOINTS = ("/api/v1/summary", "/api/v1/nodes", "/api/v1/slices")
    CLIENTS = 16
    ROUNDS = 25

    def test_no_torn_reads_no_500s_etag_stable_within_round(self, server):
        # The client loop + bijection checks live in tests/fixtures.py
        # (hammer_fleet_api / assert_poll_contract) so the serving-scale
        # tests and bench.py's load harness hammer with the SAME contract.
        nodes = fx.tpu_v5p_64_slice()[:8]
        result = _result(nodes)
        server.publish(result)
        paths = self.ENDPOINTS + (
            "/api/v1/nodes/" + nodes[0]["metadata"]["name"],
        )

        def swaps():
            # Swap ROUNDS snapshots under the pollers — no pacing, the
            # tightest interleave we can produce.
            for _ in range(self.ROUNDS):
                server.publish(result)

        flat = fx.hammer_fleet_api(
            server.port, paths, swaps, clients=self.CLIENTS
        )
        rounds_seen = fx.assert_poll_contract(flat)
        # Distinct rounds were actually observed mid-flight.
        assert len(rounds_seen) > 1


# ---------------------------------------------------------------------------
# Write path: auth + evidence gating + the live PATCH
# ---------------------------------------------------------------------------


@pytest.fixture
def fake_api(tmp_path):
    """Fake API server recording PATCHes + a kubeconfig pointing at it
    (same seam as tests/test_cordon.py / test_history_fsm.py)."""
    from http.server import BaseHTTPRequestHandler

    patches = []

    class Handler(BaseHTTPRequestHandler):
        def do_PATCH(self):
            body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
            patches.append({"path": self.path, "body": json.loads(body)})
            self.send_response(200)
            self.send_header("Content-Length", "2")
            self.end_headers()
            self.wfile.write(b"{}")

        def log_message(self, *args):
            pass

    srv = fx.serve_http(Handler)
    kubeconfig = tmp_path / "kubeconfig"
    kubeconfig.write_text(
        "apiVersion: v1\nkind: Config\ncurrent-context: t\n"
        "contexts: [{name: t, context: {cluster: t, user: t}}]\n"
        "clusters: [{name: t, cluster: {server: "
        f'"http://127.0.0.1:{srv.server_address[1]}"}}}}]\n'
        "users: [{name: t, user: {token: tok}}]\n"
    )
    yield {"patches": patches, "kubeconfig": str(kubeconfig)}
    srv.shutdown()


def _tpu_node(name="tpu-0", **kw):
    return fx.make_node(
        name,
        allocatable={"google.com/tpu": "4"},
        labels={
            "cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice",
            "cloud.google.com/gke-nodepool": "p",
        },
        **kw,
    )


def _probe_dir(tmp_path, verdicts, tag):
    d = tmp_path / f"probes-{tag}"
    d.mkdir()
    for host, ok in verdicts.items():
        (d / f"{host}.json").write_text(
            json.dumps(
                {
                    "ok": ok,
                    "level": "compute",
                    "hostname": host,
                    "written_at": time.time(),
                    "error": None if ok else "matmul numerics failed",
                }
            )
        )
    return str(d)


class TestWriteDecision:
    """Unit matrix over checker._api_write_decision — the evidence rules."""

    def _node(self, **kw):
        base = {
            "name": "tpu-0", "ready": True, "schedulable": True,
            "cordoned": False,
        }
        base.update(kw)
        return base

    def test_cordon_needs_failed_probe_evidence(self):
        ok, why = checker._api_write_decision(
            self._node(probe={"ok": False, "level": "compute"}), "cordon"
        )
        assert ok, why
        assert not checker._api_write_decision(self._node(), "cordon")[0]
        assert not checker._api_write_decision(
            self._node(probe={"ok": True, "level": "compute"}), "cordon"
        )[0]
        # Absence is not evidence — same rule as the sweep.
        assert not checker._api_write_decision(
            self._node(probe={"ok": False, "level": "missing"}), "cordon"
        )[0]

    def test_cordon_refuses_notready_cordoned_unschedulable(self):
        probe = {"ok": False, "level": "compute"}
        for node in (
            self._node(ready=False, probe=probe),
            self._node(cordoned=True, probe=probe),
            self._node(schedulable=False, probe=probe),
        ):
            ok, _ = checker._api_write_decision(node, "cordon")
            assert not ok

    def test_cordon_fsm_gated_when_history_rides(self):
        probe = {"ok": False, "level": "compute"}
        suspect = self._node(probe=probe, health={"state": "SUSPECT", "streak": 1})
        ok, why = checker._api_write_decision(suspect, "cordon")
        assert not ok and "SUSPECT" in why
        failed = self._node(probe=probe, health={"state": "FAILED", "streak": 2})
        assert checker._api_write_decision(failed, "cordon")[0]
        chronic = self._node(probe=probe, health={"state": "CHRONIC", "streak": 0})
        assert checker._api_write_decision(chronic, "cordon")[0]

    def test_uncordon_needs_our_annotation_and_passing_probe(self):
        good = self._node(
            cordoned=True, quarantined_by_us=True,
            probe={"ok": True, "level": "compute"},
        )
        assert checker._api_write_decision(good, "uncordon")[0]
        human = self._node(cordoned=True, probe={"ok": True, "level": "compute"})
        ok, why = checker._api_write_decision(human, "uncordon")
        assert not ok and "human" in why
        no_probe = self._node(cordoned=True, quarantined_by_us=True)
        assert not checker._api_write_decision(no_probe, "uncordon")[0]
        assert not checker._api_write_decision(self._node(), "uncordon")[0]

    def test_uncordon_fsm_gated_chronic_never_lifts(self):
        base = dict(
            cordoned=True, quarantined_by_us=True,
            probe={"ok": True, "level": "compute"},
        )
        recovering = self._node(**base, health={"state": "RECOVERING", "streak": 1})
        ok, why = checker._api_write_decision(recovering, "uncordon")
        assert not ok and "RECOVERING" in why
        chronic = self._node(**base, health={"state": "CHRONIC", "streak": 5})
        ok, why = checker._api_write_decision(chronic, "uncordon")
        assert not ok and "CHRONIC" in why
        healthy = self._node(**base, health={"state": "HEALTHY", "streak": 3})
        assert checker._api_write_decision(healthy, "uncordon")[0]


class TestWriteAuthEndToEnd:
    def _server(self, tmp_path, fake_api, token, tag="w", node_ok=False,
                history=True):
        extra = [
            "--kubeconfig", fake_api["kubeconfig"],
            "--probe-results", _probe_dir(tmp_path, {"tpu-0": node_ok}, tag),
        ]
        if history:
            extra += ["--history", str(tmp_path / f"history-{tag}.jsonl")]
        args = cli.parse_args(["--json", *extra])
        result = checker.run_check(args, nodes=[_tpu_node()])
        srv = FleetStateServer(
            0, host="127.0.0.1", token=token,
            control=checker._make_serve_control(args),
        )
        srv.publish(result)
        return srv

    def test_no_token_configured_writes_403(self, tmp_path, fake_api):
        srv = self._server(tmp_path, fake_api, token=None)
        try:
            status, _, body = _req(
                srv.port, "POST", "/api/v1/nodes/tpu-0/cordon",
                {"Authorization": "Bearer guessed"},
            )
            assert status == 403
            assert "disabled" in json.loads(body)["error"]
            assert fake_api["patches"] == []
            assert srv.stats.auth_failures == 1
        finally:
            srv.close()

    def test_bad_token_401_with_challenge(self, tmp_path, fake_api):
        srv = self._server(tmp_path, fake_api, token="s3cret")
        try:
            status, headers, _ = _req(srv.port, "POST", "/api/v1/nodes/tpu-0/cordon")
            assert status == 401 and headers["WWW-Authenticate"] == "Bearer"
            status, headers, _ = _req(
                srv.port, "POST", "/api/v1/nodes/tpu-0/cordon",
                {"Authorization": "Bearer wrong"},
            )
            assert status == 401
            assert fake_api["patches"] == []
            assert srv.stats.auth_failures == 2
        finally:
            srv.close()

    def test_good_token_fsm_gated_patch_lands_exactly_once(
        self, tmp_path, fake_api
    ):
        # K=1 default: one failed probed round → FAILED → cordon-eligible.
        srv = self._server(tmp_path, fake_api, token="s3cret")
        try:
            status, _, body = _req(
                srv.port, "POST", "/api/v1/nodes/tpu-0/cordon",
                {"Authorization": "Bearer s3cret"},
            )
            doc = json.loads(body)
            assert status == 200, doc
            assert doc["applied"] is True and doc["eligible"] is True
            # Exactly ONE PATCH observed server-side, with the cordon body.
            assert [p["path"] for p in fake_api["patches"]] == [
                "/api/v1/nodes/tpu-0"
            ]
            assert fake_api["patches"][0]["body"]["spec"] == {
                "unschedulable": True
            }
        finally:
            srv.close()

    def test_dry_run_decides_without_patching(self, tmp_path, fake_api):
        srv = self._server(tmp_path, fake_api, token="s3cret")
        try:
            status, _, body = _req(
                srv.port, "POST", "/api/v1/nodes/tpu-0/cordon?dry_run=1",
                {"Authorization": "Bearer s3cret"},
            )
            doc = json.loads(body)
            assert status == 200 and doc["would_apply"] is True
            assert doc["applied"] is False and doc["dry_run"] is True
            assert fake_api["patches"] == []
        finally:
            srv.close()

    def test_healthy_node_409_no_patch(self, tmp_path, fake_api):
        srv = self._server(tmp_path, fake_api, token="s3cret", node_ok=True)
        try:
            status, _, body = _req(
                srv.port, "POST", "/api/v1/nodes/tpu-0/cordon",
                {"Authorization": "Bearer s3cret"},
            )
            doc = json.loads(body)
            assert status == 409 and doc["eligible"] is False
            assert fake_api["patches"] == []
        finally:
            srv.close()

    def test_unknown_node_404_store_mode_503(self, tmp_path, fake_api):
        srv = self._server(tmp_path, fake_api, token="s3cret")
        try:
            assert _req(
                srv.port, "POST", "/api/v1/nodes/ghost/cordon",
                {"Authorization": "Bearer s3cret"},
            )[0] == 404
        finally:
            srv.close()
        # A store-backed server (control=None) refuses writes with 503.
        store_srv = FleetStateServer(0, host="127.0.0.1", token="s3cret")
        try:
            store_srv.publish(_result([_tpu_node()]))
            status, _, body = _req(
                store_srv.port, "POST", "/api/v1/nodes/tpu-0/cordon",
                {"Authorization": "Bearer s3cret"},
            )
            assert status == 503
            assert "recorded store" in json.loads(body)["error"]
        finally:
            store_srv.close()

    def test_auth_failures_emit_one_rate_limited_event(self, tmp_path, fake_api):
        srv = self._server(tmp_path, fake_api, token="s3cret")
        events = []
        srv.on_event = lambda kind, detail: events.append((kind, detail))
        try:
            for _ in range(3):
                _req(srv.port, "POST", "/api/v1/nodes/tpu-0/cordon")
            assert srv.stats.auth_failures == 3
            # Rate-limited AND off-thread (the hook may POST to Slack; the
            # 401 must not wait on it): three rejects inside the window →
            # exactly ONE event, delivered asynchronously.
            deadline = time.monotonic() + 5
            while not events and time.monotonic() < deadline:
                time.sleep(0.01)  # tnc: allow-test-wall-clock(bounded 5s poll for a REAL daemon thread to deliver the event; no clock to fake across threads)
            assert [k for k, _ in events] == ["auth-failure"]
        finally:
            srv.close()

    def test_cordon_max_budget_gates_api_writes(self, tmp_path, fake_api):
        # Two FAILED nodes, default --cordon-max 1: the first authenticated
        # cordon lands, the second answers 409 — a token holder cannot
        # drain the pool one request at a time (the sweep's budget rule).
        tag = "budget"
        args = cli.parse_args([
            "--json",
            "--kubeconfig", fake_api["kubeconfig"],
            "--probe-results",
            _probe_dir(tmp_path, {"tpu-0": False, "tpu-1": False}, tag),
            "--history", str(tmp_path / f"history-{tag}.jsonl"),
        ])
        result = checker.run_check(
            args, nodes=[_tpu_node("tpu-0"), _tpu_node("tpu-1")]
        )
        srv = FleetStateServer(
            0, host="127.0.0.1", token="s3cret",
            control=checker._make_serve_control(args),
        )
        srv.publish(result)
        try:
            auth = {"Authorization": "Bearer s3cret"}
            status, _, body = _req(
                srv.port, "POST", "/api/v1/nodes/tpu-0/cordon", auth
            )
            assert status == 200 and json.loads(body)["applied"] is True
            status, _, body = _req(
                srv.port, "POST", "/api/v1/nodes/tpu-1/cordon", auth
            )
            doc = json.loads(body)
            assert status == 409 and "budget exhausted" in doc["reason"]
            # Exactly the one budgeted PATCH reached the API server.
            assert [p["path"] for p in fake_api["patches"]] == [
                "/api/v1/nodes/tpu-0"
            ]
        finally:
            srv.close()


# ---------------------------------------------------------------------------
# Trend cache
# ---------------------------------------------------------------------------


class TestTrendCache:
    def _log(self, tmp_path, n=3):
        p = tmp_path / "trend.jsonl"
        lines = [
            json.dumps({"ts": 1_700_000_000.0 + 60 * i, "exit_code": 0,
                        "total_nodes": 2, "ready_nodes": 2})
            for i in range(n)
        ]
        p.write_text("\n".join(lines) + "\n")
        return p

    @staticmethod
    def _await_rebuilds(trend, n, deadline_s=10.0):
        """SWR rebuilds land on a background thread: bounded poll until the
        counter reaches ``n`` (never a fixed sleep)."""
        deadline = time.monotonic() + deadline_s
        while trend.rebuilds < n and time.monotonic() < deadline:
            time.sleep(0.005)  # tnc: allow-test-wall-clock(bounded 10s poll for the REAL tnc-trend-swr rebuild thread to commit; no clock to fake across threads)
        assert trend.rebuilds == n, trend.rebuilds

    def test_trend_served_stale_then_revalidated_on_file_change(self, tmp_path):
        path = self._log(tmp_path)
        srv = FleetStateServer(0, host="127.0.0.1", trend_path=str(path))
        try:
            srv.publish(_result([_tpu_node()]))
            status, _, body = _req(srv.port, "GET", "/api/v1/trend")
            assert status == 200 and json.loads(body)["rounds"] == 3
            assert srv._trend.rebuilds == 1
            # Same round, same file → cache hit, no re-read, no re-parse.
            for _ in range(5):
                _req(srv.port, "GET", "/api/v1/trend")
            assert srv._trend.rebuilds == 1
            assert srv._trend.stale_served == 0
            # Another process appends a round → mtime/size move → the
            # reader is served the PREVIOUS entity immediately (SWR) while
            # exactly one rebuild runs off-thread.
            with open(path, "a") as f:
                f.write(json.dumps({"ts": 1_700_000_300.0, "exit_code": 3}) + "\n")
            status, _, body = _req(srv.port, "GET", "/api/v1/trend")
            assert status == 200 and json.loads(body)["rounds"] == 3  # stale
            assert srv._trend.stale_served >= 1
            self._await_rebuilds(srv._trend, 2)
            status, _, body = _req(srv.port, "GET", "/api/v1/trend")
            assert json.loads(body)["rounds"] == 4  # revalidated
            assert srv._trend.rebuilds == 2
        finally:
            srv.close()

    def test_noop_publish_never_rebuilds(self, tmp_path):
        # The regression pin (ISSUE 15 satellite): the cache used to key
        # on (seq, file_signature), so EVERY publish re-read and
        # re-summarized an unchanged trend log.  The rebuild key is now
        # the trend-relevant content digest: a moving seq over an
        # unmoving log costs nothing.
        path = self._log(tmp_path)
        srv = FleetStateServer(0, host="127.0.0.1", trend_path=str(path))
        try:
            srv.publish(_result([_tpu_node()]))
            _req(srv.port, "GET", "/api/v1/trend")
            assert srv._trend.rebuilds == 1
            for _ in range(5):
                srv.publish(_result([_tpu_node()]))  # seq moves, file not
                _req(srv.port, "GET", "/api/v1/trend")
            assert srv._trend.rebuilds == 1
            assert srv._trend.stale_served == 0
        finally:
            srv.close()

    def test_touched_or_non_trend_rewrite_never_rebuilds(self, tmp_path):
        path = self._log(tmp_path)
        srv = FleetStateServer(0, host="127.0.0.1", trend_path=str(path))
        try:
            srv.publish(_result([_tpu_node()]))
            _req(srv.port, "GET", "/api/v1/trend")
            assert srv._trend.rebuilds == 1
            # mtime moves, content does not: the signature check misses
            # but the digest holds — no rebuild, no stale serve.
            os.utime(path, (1_700_100_000, 1_700_100_000))
            _req(srv.port, "GET", "/api/v1/trend")
            # A rewrite that changes only NON-trend fields of existing
            # lines (a post-processor annotating the log): full rescan,
            # identical projections — digest holds, zero rebuilds.
            lines = [json.loads(line)
                     for line in path.read_text().splitlines()]
            path.write_text("".join(
                json.dumps({**entry, "annotated_by": "logtool"}) + "\n"
                for entry in lines
            ))
            _req(srv.port, "GET", "/api/v1/trend")
            assert srv._trend.rebuilds == 1
            assert srv._trend.stale_served == 0
            # A REAL round line moves the digest → exactly one rebuild.
            with open(path, "a") as f:
                f.write(json.dumps(
                    {"ts": 1_700_000_300.0, "exit_code": 3}) + "\n")
            _req(srv.port, "GET", "/api/v1/trend")
            self._await_rebuilds(srv._trend, 2)
            _, _, body = _req(srv.port, "GET", "/api/v1/trend")
            assert json.loads(body)["rounds"] == 4
        finally:
            srv.close()

    def test_non_trend_append_still_moves_skipped_lines(self, tmp_path):
        # A valid-JSON line with no trend field moves the summary's
        # skipped_lines count, so it IS trend-relevant: the served body
        # must agree with what --trend computes over the same log.
        path = self._log(tmp_path)
        srv = FleetStateServer(0, host="127.0.0.1", trend_path=str(path))
        try:
            srv.publish(_result([_tpu_node()]))
            _req(srv.port, "GET", "/api/v1/trend")
            with open(path, "a") as f:
                f.write(json.dumps({"note": "rotated certs"}) + "\n")
            _req(srv.port, "GET", "/api/v1/trend")
            self._await_rebuilds(srv._trend, 2)
            _, _, body = _req(srv.port, "GET", "/api/v1/trend")
            doc = json.loads(body)
            assert doc["rounds"] == 3 and doc["skipped_lines"] == 1
        finally:
            srv.close()

    def test_transient_read_failure_does_not_skip_appended_bytes(
        self, tmp_path, monkeypatch
    ):
        # A failed digest scan must NOT commit the new file signature:
        # otherwise the sig==sig fast path would serve the pre-append
        # entity forever (until the log happens to move again).
        from tpu_node_checker.history import store as store_mod

        path = self._log(tmp_path)
        srv = FleetStateServer(0, host="127.0.0.1", trend_path=str(path))
        try:
            srv.publish(_result([_tpu_node()]))
            _req(srv.port, "GET", "/api/v1/trend")
            assert srv._trend.rebuilds == 1
            with open(path, "a") as f:
                f.write(json.dumps(
                    {"ts": 1_700_000_300.0, "exit_code": 3}) + "\n")
            real_tail = store_mod.read_jsonl_tail

            def boom(*a, **kw):
                raise OSError("transient rotation race")

            monkeypatch.setattr(store_mod, "read_jsonl_tail", boom)
            _, _, body = _req(srv.port, "GET", "/api/v1/trend")
            assert json.loads(body)["rounds"] == 3  # old entity, no crash
            monkeypatch.setattr(store_mod, "read_jsonl_tail", real_tail)
            # The next request retries the scan and sees the append.
            _req(srv.port, "GET", "/api/v1/trend")
            self._await_rebuilds(srv._trend, 2)
            _, _, body = _req(srv.port, "GET", "/api/v1/trend")
            assert json.loads(body)["rounds"] == 4
        finally:
            srv.close()

    def test_empty_or_missing_log_is_machine_readable(self, tmp_path):
        srv = FleetStateServer(
            0, host="127.0.0.1", trend_path=str(tmp_path / "absent.jsonl")
        )
        try:
            srv.publish(_result([_tpu_node()]))
            status, _, body = _req(srv.port, "GET", "/api/v1/trend")
            doc = json.loads(body)
            assert status == 200 and doc["rounds"] == 0 and doc["error"]
        finally:
            srv.close()


# ---------------------------------------------------------------------------
# Standalone store serving + watch integration
# ---------------------------------------------------------------------------


def _capture_server(monkeypatch):
    captured = {}
    real = server_app.FleetStateServer

    def wrapper(*a, **kw):
        kw.setdefault("host", "127.0.0.1")
        srv = real(*a, **kw)
        captured["srv"] = srv
        return srv

    monkeypatch.setattr(server_app, "FleetStateServer", wrapper)
    return captured


def _store_line(node, i, ok, state, ts=1_700_000_000.0):
    return json.dumps({
        "schema": 1, "node": node, "ts": ts + 60 * i, "ok": ok,
        "causes": [] if ok else ["probe-failed"], "state": state,
        "streak": 1, "flaps": 0, "flaps_total": 0,
    })


class TestServeStore:
    def test_standalone_serves_history_store_and_tracks_rewrites(
        self, tmp_path, monkeypatch
    ):
        store = tmp_path / "history.jsonl"
        store.write_text(
            "\n".join(
                [_store_line("tpu-0", i, True, "HEALTHY") for i in range(3)]
                + [_store_line("tpu-1", i, i < 2, "HEALTHY" if i < 2 else "FAILED")
                   for i in range(3)]
            ) + "\n"
        )
        captured = _capture_server(monkeypatch)
        done = threading.Event()
        monkeypatch.setattr(
            checker, "_wait_for_next_round", lambda stop, s: done.wait(15)
        )
        args = cli.parse_args(["--serve", "0", "--history", str(store)])
        rc = []
        thread = threading.Thread(
            target=lambda: rc.append(checker.serve_store(args)),
            name="tnc-test-serve-store", daemon=True,
        )
        thread.start()
        try:
            deadline = time.monotonic() + 10
            while "srv" not in captured and time.monotonic() < deadline:
                time.sleep(0.01)  # tnc: allow-test-wall-clock(bounded 10s poll for the REAL serve_store thread to publish its server; no injectable clock across threads)
            srv = captured["srv"]
            assert _req(srv.port, "GET", "/readyz")[0] == 200
            _, _, body = _req(srv.port, "GET", "/api/v1/nodes")
            doc = json.loads(body)
            assert doc["count"] == 2 and doc["source"] == "history-store"
            _, _, body = _req(srv.port, "GET", "/api/v1/nodes/tpu-1")
            node = json.loads(body)["node"]
            assert node["health"]["state"] == "FAILED"
            assert node["causes"] == ["probe-failed"]
            _, _, body = _req(srv.port, "GET", "/api/v1/summary")
            summary = json.loads(body)
            assert summary["states"] == {"HEALTHY": 1, "FAILED": 1}
            # Writes: no live round → 503 even with... no token here → 403
            assert _req(srv.port, "POST", "/api/v1/nodes/tpu-1/uncordon")[0] == 403
            # The owning process writes another round → served on next poll.
            with open(store, "a") as f:
                f.write(_store_line("tpu-1", 3, True, "RECOVERING") + "\n")
            _, _, body = _req(srv.port, "GET", "/api/v1/nodes/tpu-1")
            assert json.loads(body)["node"]["health"]["state"] == "RECOVERING"
        finally:
            done.set()
            thread.join(timeout=10)
        assert rc == [128 + 15]

    def test_trendlog_only_mode_summary_degrades_honestly(
        self, tmp_path, monkeypatch
    ):
        log = tmp_path / "trend.jsonl"
        log.write_text(
            json.dumps({"ts": 1_700_000_000.0, "exit_code": 0,
                        "total_nodes": 4, "ready_nodes": 4}) + "\n"
            + json.dumps({"ts": 1_700_000_060.0, "exit_code": 3,
                          "total_nodes": 4, "ready_nodes": 3,
                          "causes": ["probe-failed: tpu-2"]}) + "\n"
        )
        captured = _capture_server(monkeypatch)
        done = threading.Event()
        monkeypatch.setattr(
            checker, "_wait_for_next_round", lambda stop, s: done.wait(15)
        )
        args = cli.parse_args(["--serve", "0", "--log-jsonl", str(log)])
        thread = threading.Thread(
            target=lambda: checker.serve_store(args),
            name="tnc-test-serve-store", daemon=True,
        )
        thread.start()
        try:
            deadline = time.monotonic() + 10
            while "srv" not in captured and time.monotonic() < deadline:
                time.sleep(0.01)  # tnc: allow-test-wall-clock(bounded 10s poll for the REAL serve_store thread to publish its server; no injectable clock across threads)
            srv = captured["srv"]
            _, _, body = _req(srv.port, "GET", "/api/v1/summary")
            summary = json.loads(body)
            assert summary["source"] == "trend-log"
            assert summary["exit_code"] == 3 and summary["healthy"] is False
            assert summary["causes"] == ["probe-failed: tpu-2"]
            _, _, body = _req(srv.port, "GET", "/api/v1/nodes")
            assert json.loads(body)["count"] == 0
            # /api/v1/trend serves the full summary over the same log.
            _, _, body = _req(srv.port, "GET", "/api/v1/trend")
            assert json.loads(body)["rounds"] == 2
            assert _req(srv.port, "GET", "/readyz")[0] == 200
        finally:
            done.set()
            thread.join(timeout=10)

    def test_empty_store_stays_not_ready(self, tmp_path, monkeypatch):
        store = tmp_path / "empty.jsonl"
        store.write_text("")
        captured = _capture_server(monkeypatch)
        done = threading.Event()
        monkeypatch.setattr(
            checker, "_wait_for_next_round", lambda stop, s: done.wait(15)
        )
        args = cli.parse_args(["--serve", "0", "--history", str(store)])
        thread = threading.Thread(
            target=lambda: checker.serve_store(args),
            name="tnc-test-serve-store", daemon=True,
        )
        thread.start()
        try:
            deadline = time.monotonic() + 10
            while "srv" not in captured and time.monotonic() < deadline:
                time.sleep(0.01)  # tnc: allow-test-wall-clock(bounded 10s poll for the REAL serve_store thread to publish its server; no injectable clock across threads)
            srv = captured["srv"]
            assert _req(srv.port, "GET", "/readyz")[0] == 503
            assert _req(srv.port, "GET", "/api/v1/nodes")[0] == 503
            assert _req(srv.port, "GET", "/healthz")[0] == 200
        finally:
            done.set()
            thread.join(timeout=10)


class TestServeCliValidation:
    @pytest.mark.parametrize(
        "extra",
        [
            ["--probe"],
            ["--strict-slices"],
            ["--nodes-json", "n.json"],
            ["--slack-only-on-error"],
            ["--label-selector", "x=y"],
        ],
    )
    def test_standalone_serve_rejects_round_only_flags(self, extra, capsys):
        # Standalone --serve runs no rounds: a flag that only acts during
        # a round must be rejected, not silently absorbed (the repo's
        # silent-no-op rule).
        with pytest.raises(SystemExit):
            cli.parse_args(["--serve", "0", "--history", "h.jsonl", *extra])
        assert "runs no check rounds" in capsys.readouterr().err

    def test_watch_serve_accepts_round_flags(self):
        args = cli.parse_args(
            ["--watch", "5", "--serve", "0", "--probe", "--strict-slices"]
        )
        assert args.serve == 0 and args.probe

    def test_standalone_serve_with_store_flags_parses(self):
        args = cli.parse_args(
            ["--serve", "0", "--history", "h.jsonl", "--log-jsonl", "t.jsonl",
             "--serve-token", "s"]
        )
        assert args.serve == 0


class TestWatchIntegration:
    def test_watch_publishes_every_round_and_closes_on_exit(
        self, tmp_path, monkeypatch, capsys
    ):
        nodes = [_tpu_node()]
        captured = _capture_server(monkeypatch)
        observed = []

        def fake_fetch(args, timer):
            return [json.loads(json.dumps(n)) for n in nodes], None

        def fake_wait(stop, s):
            srv = captured["srv"]
            status, _, body = _req(srv.port, "GET", "/api/v1/summary")
            observed.append((status, json.loads(body)["round"]))
            assert _req(srv.port, "GET", "/readyz")[0] == 200
            return len(observed) >= 3

        monkeypatch.setattr(checker, "_fetch_nodes", fake_fetch)
        monkeypatch.setattr(checker, "_wait_for_next_round", fake_wait)
        args = cli.parse_args(["--watch", "10", "--serve", "0", "--json"])
        assert checker.watch(args) == 128 + 15
        assert observed == [(200, 1), (200, 2), (200, 3)]
        # The finally closed the server: the port no longer accepts.
        with pytest.raises(OSError):
            _req(captured["srv"].port, "GET", "/healthz")


# ---------------------------------------------------------------------------
# No --serve → nothing changes (the PR's regression contract)
# ---------------------------------------------------------------------------


class TestNoServeByteIdentical:
    def test_payload_and_metrics_identical_without_serve_surface(self, capsys):
        from tpu_node_checker.metrics import render_metrics

        nodes = fx.tpu_v5e_256_slice()

        def run(args):
            code = checker.one_shot(
                args, nodes=[json.loads(json.dumps(n)) for n in nodes]
            )
            return code, json.loads(capsys.readouterr().out)

        args_flag = cli.parse_args(["--json"])  # serve=None on the namespace
        args_bare = cli.parse_args(["--json"])
        # Simulate the pre-serve flag surface entirely absent: the check
        # path must consult nothing serve-related.
        delattr(args_bare, "serve")
        delattr(args_bare, "serve_token")
        code_a, a = run(args_flag)
        code_b, b = run(args_bare)
        assert code_a == code_b
        a.pop("timings_ms"), b.pop("timings_ms")
        # Per-round identity, different by construction between the runs.
        a.pop("trace_id"), b.pop("trace_id")
        assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)

        def strip_volatile(text):
            return "\n".join(
                line for line in text.splitlines()
                if not line.startswith(
                    ("tpu_node_checker_last_run_timestamp_seconds ",
                     "tpu_node_checker_check_duration_ms ")
                )
            )

        result_a = checker.run_check(
            args_flag, nodes=[json.loads(json.dumps(n)) for n in nodes]
        )
        result_b = checker.run_check(
            args_bare, nodes=[json.loads(json.dumps(n)) for n in nodes]
        )
        assert strip_volatile(render_metrics(result_a)) == strip_volatile(
            render_metrics(result_b)
        )


# ---------------------------------------------------------------------------
# --metrics-port satellite: routed, HEAD, ETag, gzip
# ---------------------------------------------------------------------------


class TestMetricsServerRouted:
    def _server(self):
        from tpu_node_checker.metrics import MetricsServer

        return MetricsServer(0, host="127.0.0.1")

    def test_unknown_path_404_root_alias_kept(self):
        srv = self._server()
        try:
            assert _req(srv.port, "GET", "/nope")[0] == 404
            assert _req(srv.port, "GET", "/")[0] == 200
            assert _req(srv.port, "GET", "/metrics")[0] == 200
        finally:
            srv.close()

    def test_head_etag_gzip_on_metrics(self):
        srv = self._server()
        try:
            srv.update(_result())
            g_status, g_headers, g_body = _req(srv.port, "GET", "/metrics")
            assert g_status == 200
            assert g_headers["Content-Type"].startswith("text/plain")
            assert b"tpu_node_checker_chips" in g_body
            # HEAD: the GET's headers, no body.
            h_status, h_headers, h_body = _req(srv.port, "HEAD", "/metrics")
            assert (h_status, h_body) == (200, b"")
            assert h_headers["Content-Length"] == str(len(g_body))
            # ETag: stable between scrapes of the same round, 304 on match.
            etag = g_headers["ETag"]
            status, _, _ = _req(
                srv.port, "GET", "/metrics", {"If-None-Match": etag}
            )
            assert status == 304
            # A new round swaps the body → the old ETag misses.
            srv.update(_result(fx.tpu_v5e_256_slice(not_ready=1)))
            status, headers, _ = _req(
                srv.port, "GET", "/metrics", {"If-None-Match": etag}
            )
            assert status == 200 and headers["ETag"] != etag
            # gzip negotiation.
            status, headers, body = _req(
                srv.port, "GET", "/metrics", {"Accept-Encoding": "gzip"}
            )
            assert headers.get("Content-Encoding") == "gzip"
            assert b"tpu_node_checker_chips" in gzip.decompress(body)
        finally:
            srv.close()

    def test_served_bytes_equal_render_metrics_output(self):
        # The router layer must not mutate the scrape body by a byte
        # (modulo the wall-clock staleness stamp, which moves per render).
        from tpu_node_checker.metrics import render_metrics

        def stable(text: bytes) -> list:
            return [
                line
                for line in text.splitlines()
                if not line.startswith(b"tpu_node_checker_last_run_timestamp_seconds ")
            ]

        srv = self._server()
        try:
            result = _result()
            srv.update(result)
            _, _, body = _req(srv.port, "GET", "/metrics")
            assert stable(body) == stable(render_metrics(result).encode())
        finally:
            srv.close()


class TestStoreSnapshotUnit:
    def test_build_store_snapshot_rolls_up_latest_lines(self, tmp_path):
        store = tmp_path / "s.jsonl"
        store.write_text(
            _store_line("a", 0, True, "HEALTHY") + "\n"
            + _store_line("a", 1, False, "SUSPECT") + "\n"
            + "{torn\n"
            + _store_line("b", 0, False, "CHRONIC") + "\n"
        )
        snap = build_store_snapshot(str(store), 7, 1_700_000_999.0)
        summary = json.loads(snap.entities["summary"].raw)
        assert summary["total_nodes"] == 2
        assert summary["states"] == {"SUSPECT": 1, "CHRONIC": 1}
        assert summary["chronic"] == ["b"]
        assert summary["skipped_lines"] == 1
        assert json.loads(snap.node_entities["a"].raw)["node"]["health"][
            "state"
        ] == "SUSPECT"

    def test_build_snapshot_etag_differs_across_seq(self):
        payload = _result([_tpu_node()]).payload
        one = build_snapshot(payload, 0, 1, 1_700_000_000.0)
        two = build_snapshot(payload, 0, 2, 1_700_000_060.0)
        for key in ("summary", "nodes", "slices"):
            assert one.entities[key].etag != two.entities[key].etag
