"""Hysteresis FSM tests: unit-level state machine + the wired quarantine
lifecycle (--history gating cordon/uncordon, the CHRONIC flap trap, Slack
transitions, metrics, --trend-nodes) against a fake API server.
"""

import json
import time
from http.server import BaseHTTPRequestHandler

import pytest

from tests import fixtures as fx
from tpu_node_checker import checker, cli, notify
from tpu_node_checker.history.fsm import (
    CHRONIC,
    FAILED,
    HEALTHY,
    HealthFSM,
    RECOVERING,
    SUSPECT,
)


class TestHealthFSMUnit:
    def test_defaults_collapse_to_per_round_behavior(self):
        # K = M = 1: one bad round lands FAILED, one good round lands
        # HEALTHY — exactly the pre-history snapshot policy.
        fsm = HealthFSM()
        assert fsm.observe("n", False) == (HEALTHY, FAILED)
        assert fsm.cordon_eligible("n")
        assert fsm.observe("n", True) == (FAILED, HEALTHY)
        assert fsm.uncordon_eligible("n")

    def test_cordon_after_debounces(self):
        fsm = HealthFSM(cordon_after=3)
        assert fsm.observe("n", False) == (HEALTHY, SUSPECT)
        assert not fsm.cordon_eligible("n")
        assert fsm.observe("n", False) is None  # SUSPECT, streak 2
        assert fsm.observe("n", False) == (SUSPECT, FAILED)
        assert fsm.cordon_eligible("n")

    def test_one_good_round_clears_suspect(self):
        fsm = HealthFSM(cordon_after=3)
        fsm.observe("n", False)
        assert fsm.observe("n", True) == (SUSPECT, HEALTHY)
        # The bad streak restarted: two MORE bad rounds are not enough.
        fsm.observe("n", False)
        fsm.observe("n", False)
        assert fsm.health("n").state == SUSPECT

    def test_uncordon_after_requires_consecutive_good(self):
        fsm = HealthFSM(uncordon_after=3, flap_threshold=10, flap_window=10)
        fsm.observe("n", False)  # FAILED (K=1)
        assert fsm.observe("n", True) == (FAILED, RECOVERING)
        assert not fsm.uncordon_eligible("n")
        fsm.observe("n", True)
        assert fsm.health("n").state == RECOVERING
        assert fsm.observe("n", True) == (RECOVERING, HEALTHY)
        assert fsm.uncordon_eligible("n")

    def test_bad_round_mid_recovery_restarts_the_clock(self):
        fsm = HealthFSM(cordon_after=2, uncordon_after=2,
                        flap_threshold=10, flap_window=10)
        fsm.observe("n", False)
        fsm.observe("n", False)  # FAILED (K=2)
        fsm.observe("n", True)  # RECOVERING streak 1 (< M)
        assert fsm.observe("n", False) == (RECOVERING, SUSPECT)
        # The good streak is gone: recovery restarts from scratch.
        fsm.observe("n", False)
        assert fsm.health("n").state == FAILED

    def test_flap_detector_trips_chronic_and_sticks(self):
        fsm = HealthFSM(cordon_after=2, uncordon_after=3)
        verdicts = [False, True, False, True, False]
        for v in verdicts[:-1]:
            fsm.observe("n", v)
            assert fsm.health("n").state != CHRONIC
        assert fsm.observe("n", verdicts[-1]) == (HEALTHY, CHRONIC)
        assert fsm.cordon_eligible("n")
        # Sticky: good rounds never lift CHRONIC.
        for _ in range(10):
            fsm.observe("n", True)
        assert fsm.health("n").state == CHRONIC
        assert not fsm.uncordon_eligible("n")

    def test_out_of_band_uncordon_resets_to_recovering_not_healthy(self):
        fsm = HealthFSM(uncordon_after=3, flap_threshold=10, flap_window=10)
        fsm.observe("n", False)  # FAILED
        t = fsm.observe("n", True, uncordoned_out_of_band=True)
        assert t == (FAILED, RECOVERING)
        assert fsm.health("n").state == RECOVERING
        assert not fsm.uncordon_eligible("n")

    def test_out_of_band_releases_chronic_into_recovering(self):
        fsm = HealthFSM(uncordon_after=2)
        for v in [False, True, False, True, False]:
            fsm.observe("n", v)
        assert fsm.health("n").state == CHRONIC
        fsm.observe("n", True, uncordoned_out_of_band=True)
        assert fsm.health("n").state == RECOVERING
        t = [x for x in fsm.transitions if x["from"] == CHRONIC]
        assert t and t[-1]["actionable"]

    def test_none_verdict_holds_all_state(self):
        fsm = HealthFSM(cordon_after=2)
        fsm.observe("n", False)
        h_before = (fsm.health("n").state, fsm.health("n").streak,
                    list(fsm.health("n").verdicts))
        assert fsm.observe("n", None) is None
        h_after = (fsm.health("n").state, fsm.health("n").streak,
                   list(fsm.health("n").verdicts))
        assert h_before == h_after

    def test_actionable_classification(self):
        fsm = HealthFSM(cordon_after=2, uncordon_after=2,
                        flap_threshold=10, flap_window=10)
        for v in [False, False, True, True]:
            fsm.observe("n", v)
        flagged = {(t["from"], t["to"]): t["actionable"] for t in fsm.transitions}
        assert flagged[(HEALTHY, SUSPECT)] is False
        assert flagged[(SUSPECT, FAILED)] is True
        assert flagged[(FAILED, RECOVERING)] is False
        assert flagged[(RECOVERING, HEALTHY)] is True

    def test_seed_restores_state_and_flap_window(self):
        fsm = HealthFSM(cordon_after=2, flap_threshold=4, flap_window=10)
        entries = [
            {"ok": ok, "state": SUSPECT, "streak": 1, "flaps_total": 3}
            for ok in [False, True, False, True]
        ]
        fsm.seed("n", entries)
        h = fsm.health("n")
        assert h.state == SUSPECT and h.flaps == 3 and h.flaps_total == 3
        # The next flip is the fourth inside the window: CHRONIC.
        fsm.observe("n", False)
        assert h.state == CHRONIC

    def test_seed_unknown_state_degrades_to_healthy(self):
        fsm = HealthFSM()
        fsm.seed("n", [{"ok": False, "state": "BOGUS_FUTURE_STATE", "streak": 9}])
        assert fsm.health("n").state == HEALTHY
        assert fsm.health("n").streak == 0

    def test_state_counts_cover_every_state(self):
        fsm = HealthFSM()
        fsm.observe("a", False)
        counts = fsm.state_counts()
        assert counts[FAILED] == 1
        assert set(counts) == {HEALTHY, SUSPECT, FAILED, RECOVERING, CHRONIC}


@pytest.fixture
def fake_api(tmp_path):
    """Fake API server recording PATCHes + a kubeconfig pointing at it
    (same seam as tests/test_cordon.py)."""
    patches = []

    class Handler(BaseHTTPRequestHandler):
        def do_PATCH(self):
            body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
            patches.append({"path": self.path, "body": json.loads(body)})
            self.send_response(200)
            self.send_header("Content-Length", "2")
            self.end_headers()
            self.wfile.write(b"{}")

        def log_message(self, *args):
            pass

    server = fx.serve_http(Handler)
    kubeconfig = tmp_path / "kubeconfig"
    kubeconfig.write_text(
        "apiVersion: v1\nkind: Config\ncurrent-context: t\n"
        "contexts: [{name: t, context: {cluster: t, user: t}}]\n"
        "clusters: [{name: t, cluster: {server: "
        f'"http://127.0.0.1:{server.server_address[1]}"}}}}]\n'
        "users: [{name: t, user: {token: tok}}]\n"
    )
    yield {"patches": patches, "kubeconfig": str(kubeconfig)}
    server.shutdown()


def _tpu_node(name="tpu-0", **kw):
    return fx.make_node(
        name,
        allocatable={"google.com/tpu": "4"},
        labels={
            "cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice",
            "cloud.google.com/gke-nodepool": "p",
        },
        **kw,
    )


def _probe_dir(tmp_path, verdicts, tag):
    d = tmp_path / f"probes-{tag}"
    d.mkdir()
    for host, ok in verdicts.items():
        (d / f"{host}.json").write_text(
            json.dumps(
                {
                    "ok": ok,
                    "level": "compute",
                    "hostname": host,
                    "written_at": time.time(),
                    "error": None if ok else "matmul numerics failed",
                }
            )
        )
    return str(d)


class TestFlapScenario:
    """The acceptance scenario: one node alternating fail/pass per round
    under K=2 / M=3 produces exactly one cordon PATCH, zero uncordon
    PATCHes, one Slack CHRONIC-transition message, and ends CHRONIC."""

    def test_alternating_node_is_trapped_not_churned(
        self, tmp_path, fake_api, monkeypatch, capsys
    ):
        sent = []
        monkeypatch.setattr(
            notify, "send_slack_message",
            lambda url, message, **kw: sent.append(message) or True,
        )
        hist = str(tmp_path / "history.jsonl")
        cordoned = False  # mirrors what the fake API applied
        final_payload = None
        for i, ok in enumerate([False, True, False, True, False, True, False]):
            nodes = [_tpu_node(unschedulable=cordoned)]
            if cordoned:
                nodes[0]["metadata"]["annotations"] = {
                    "tpu-node-checker.io/quarantined": "1700000000"
                }
            nodes_json = tmp_path / f"nodes-{i}.json"
            nodes_json.write_text(json.dumps(fx.node_list(nodes)))
            args = cli.parse_args(
                [
                    "--nodes-json", str(nodes_json),
                    "--kubeconfig", fake_api["kubeconfig"],
                    "--probe-results", _probe_dir(tmp_path, {"tpu-0": ok}, i),
                    "--history", hist,
                    "--cordon-after", "2",
                    "--uncordon-after", "3",
                    "--cordon-failed", "--uncordon-recovered",
                    "--slack-webhook", "https://hooks.example/x",
                    "--json",
                ]
            )
            checker.one_shot(args)
            final_payload = json.loads(capsys.readouterr().out)
            if final_payload["cordon"]["cordoned"]:
                cordoned = True
            if final_payload["uncordon"]["uncordoned"]:
                cordoned = False
        # Exactly ONE PATCH total: the cordon at the CHRONIC transition —
        # no cordon/uncordon churn, despite seven alternating rounds.
        assert [p["path"] for p in fake_api["patches"]] == ["/api/v1/nodes/tpu-0"]
        assert fake_api["patches"][0]["body"]["spec"] == {"unschedulable": True}
        # Exactly one Slack message carries the CHRONIC transition line.
        assert sum("went CHRONIC" in m for m in sent) == 1
        # The node ends CHRONIC, visible on every surface.
        assert final_payload["nodes"][0]["health"]["state"] == "CHRONIC"
        assert final_payload["history"]["chronic"] == ["tpu-0"]
        assert final_payload["history"]["states"]["CHRONIC"] == 1

    def test_without_history_payload_is_byte_identical(self, tmp_path, capsys):
        # The no-flag contract on the 8-node fixture: --history absent →
        # the payload has no history key and no per-node health entries,
        # and turning the flag ON changes NOTHING else — stripping the two
        # additive keys (and the wall-clock timings) yields byte-identical
        # JSON and the same exit code.
        nodes = fx.tpu_v5p_64_slice()[:8]

        def run(extra=()):
            args = cli.parse_args(["--json", *extra])
            code = checker.one_shot(
                args, nodes=[json.loads(json.dumps(n)) for n in nodes]
            )
            return code, json.loads(capsys.readouterr().out)

        code_off, off = run()
        code_on, on = run(["--history", str(tmp_path / "h.jsonl")])
        assert "history" not in off
        assert all("health" not in n for n in off["nodes"])
        assert code_on == code_off
        on.pop("history")
        for n in on["nodes"]:
            n.pop("health")
        off.pop("timings_ms"), on.pop("timings_ms")
        # Per-round identity, different by construction between the runs.
        off.pop("trace_id"), on.pop("trace_id")
        assert json.dumps(on, sort_keys=True) == json.dumps(off, sort_keys=True)

    def test_chronic_rides_trend_causes(self, tmp_path, capsys):
        # A CHRONIC node is an exit-3-style cause: when the fleet grades
        # degraded, the cause list names the flapper as its own class.
        from tpu_node_checker.checker import _cause_class, _round_causes

        payload = {
            "nodes": [{"name": "tpu-0", "ready": False}],
            "history": {"chronic": ["tpu-0"]},
        }
        causes = _round_causes(payload)
        assert "chronic-flapper: tpu-0" in causes
        assert _cause_class("chronic-flapper: tpu-0") == "chronic-flapper"


class TestHysteresisGating:
    def _run(self, tmp_path, fake_api, capsys, tag, ok, extra=(), node=None):
        nodes_json = tmp_path / f"nodes-{tag}.json"
        nodes_json.write_text(json.dumps(fx.node_list([node or _tpu_node()])))
        args = cli.parse_args(
            [
                "--nodes-json", str(nodes_json),
                "--kubeconfig", fake_api["kubeconfig"],
                "--probe-results", _probe_dir(tmp_path, {"tpu-0": ok}, tag),
                "--history", str(tmp_path / "history.jsonl"),
                "--json",
                *extra,
            ]
        )
        code = checker.one_shot(args)
        return code, json.loads(capsys.readouterr().out)

    def test_single_bad_round_under_k2_is_not_cordoned(
        self, tmp_path, fake_api, capsys
    ):
        _, payload = self._run(
            tmp_path, fake_api, capsys, 0, ok=False,
            extra=["--cordon-after", "2", "--cordon-failed"],
        )
        assert fake_api["patches"] == []
        assert payload["cordon"]["cordoned"] == []
        assert payload["nodes"][0]["health"]["state"] == "SUSPECT"

    def test_kth_consecutive_bad_round_cordons(self, tmp_path, fake_api, capsys):
        self._run(tmp_path, fake_api, capsys, 0, ok=False,
                  extra=["--cordon-after", "2", "--cordon-failed"])
        _, payload = self._run(
            tmp_path, fake_api, capsys, 1, ok=False,
            extra=["--cordon-after", "2", "--cordon-failed"],
        )
        assert [p["path"] for p in fake_api["patches"]] == ["/api/v1/nodes/tpu-0"]
        assert payload["cordon"]["cordoned"] == ["tpu-0"]
        assert payload["nodes"][0]["health"]["state"] == "FAILED"

    def test_quarantined_node_needs_m_good_rounds_to_lift(
        self, tmp_path, fake_api, capsys
    ):
        q = _tpu_node(unschedulable=True)
        q["metadata"]["annotations"] = {
            "tpu-node-checker.io/quarantined": "1700000000"
        }
        extra = ["--uncordon-after", "3", "--uncordon-recovered"]
        # Seed the machine FAILED (while not yet cordoned in the fixture).
        self._run(tmp_path, fake_api, capsys, 0, ok=False)
        for tag in (1, 2):
            _, payload = self._run(
                tmp_path, fake_api, capsys, tag, ok=True, extra=extra,
                node=json.loads(json.dumps(q)),
            )
            assert fake_api["patches"] == []  # still RECOVERING
            assert payload["uncordon"]["uncordoned"] == []
            assert payload["nodes"][0]["health"]["state"] == "RECOVERING"
        _, payload = self._run(
            tmp_path, fake_api, capsys, 3, ok=True, extra=extra,
            node=json.loads(json.dumps(q)),
        )
        assert [p["path"] for p in fake_api["patches"]] == ["/api/v1/nodes/tpu-0"]
        assert fake_api["patches"][0]["body"]["spec"] == {"unschedulable": False}
        assert payload["uncordon"]["uncordoned"] == ["tpu-0"]
        assert payload["nodes"][0]["health"]["state"] == "HEALTHY"

    def test_out_of_band_uncordon_resets_to_recovering_and_clears_annotation(
        self, tmp_path, fake_api, capsys
    ):
        # Regression (satellite): the stale-annotation sweep and the FSM
        # must agree — `kubectl uncordon` mid-quarantine leaves the node
        # RECOVERING (re-earning HEALTHY over M rounds), never HEALTHY,
        # while the sweep strips the stale annotation.
        self._run(tmp_path, fake_api, capsys, 0, ok=False)  # FAILED
        ooband = _tpu_node()  # schedulable again, annotation left behind
        ooband["metadata"]["annotations"] = {
            "tpu-node-checker.io/quarantined": "1700000000"
        }
        _, payload = self._run(
            tmp_path, fake_api, capsys, 1, ok=True,
            extra=["--uncordon-after", "3", "--uncordon-recovered"],
            node=ooband,
        )
        assert payload["nodes"][0]["health"]["state"] == "RECOVERING"
        assert payload["uncordon"]["stale_annotations_cleared"] == ["tpu-0"]
        # The sweep's annotation-strip PATCH went out; no uncordon PATCH.
        assert len(fake_api["patches"]) == 1
        assert "spec" not in fake_api["patches"][0]["body"]

    def test_quarantined_node_without_report_holds_state(
        self, tmp_path, fake_api, capsys
    ):
        # Absence is not evidence in EITHER direction: a quarantined node
        # with no probe report this round neither heals toward
        # --uncordon-after nor accrues bad rounds.
        self._run(tmp_path, fake_api, capsys, 0, ok=False)  # FAILED
        q = _tpu_node(unschedulable=True)
        q["metadata"]["annotations"] = {
            "tpu-node-checker.io/quarantined": "1700000000"
        }
        nodes_json = tmp_path / "nodes-noreport.json"
        nodes_json.write_text(json.dumps(fx.node_list([q])))
        empty = tmp_path / "probes-empty"
        empty.mkdir()
        args = cli.parse_args(
            [
                "--nodes-json", str(nodes_json),
                "--kubeconfig", fake_api["kubeconfig"],
                "--probe-results", str(empty),
                "--history", str(tmp_path / "history.jsonl"),
                "--uncordon-after", "2", "--uncordon-recovered",
                "--json",
            ]
        )
        checker.one_shot(args)
        payload = json.loads(capsys.readouterr().out)
        assert payload["nodes"][0]["health"]["state"] == "FAILED"
        assert fake_api["patches"] == []


class TestReviewRegressions:
    def test_missing_reports_do_not_bank_rounds_toward_cordon(
        self, tmp_path, fake_api, capsys
    ):
        # K-1 rounds of ABSENT reports (--probe-results-required synthesizes
        # level="missing") plus one real failure must not reach FAILED: the
        # debounce promises K consecutive rounds of real evidence.
        hist = str(tmp_path / "history.jsonl")
        node_json = tmp_path / "nodes.json"
        node_json.write_text(json.dumps(fx.node_list([_tpu_node()])))

        def run(tag, reports):
            args = cli.parse_args(
                [
                    "--nodes-json", str(node_json),
                    "--kubeconfig", fake_api["kubeconfig"],
                    "--probe-results", reports, "--probe-results-required",
                    "--history", hist, "--cordon-after", "2",
                    "--cordon-failed", "--json",
                ]
            )
            checker.one_shot(args)
            return json.loads(capsys.readouterr().out)

        empty = tmp_path / "probes-none"
        empty.mkdir()
        p1 = run(0, str(empty))  # missing: no evidence
        # No evidence about a NEVER-observed node mints no machine at all:
        # no health key, no store line — a recorded default-HEALTHY would
        # seed uncordon-eligible state from pure absence after a restart
        # (and under --watch-stream, from mere stream silence).
        assert "health" not in p1["nodes"][0]
        assert p1["history"]["states"]["HEALTHY"] == 0
        p2 = run(1, _probe_dir(tmp_path, {"tpu-0": False}, "real"))
        # One real bad round: SUSPECT (streak 1 of 2), NOT FAILED/cordoned.
        assert p2["nodes"][0]["health"]["state"] == "SUSPECT"
        assert fake_api["patches"] == []

    def test_unwritable_store_still_advances_hysteresis_in_process(
        self, tmp_path, capsys
    ):
        # The never-fatal contract end to end: with the store path
        # unwritable (a directory), consecutive rounds in ONE process must
        # still accumulate state through the cached in-memory machine — a
        # full disk must not freeze the debounce clock.
        nodes = [_tpu_node()]

        def run(tag, ok):
            args = cli.parse_args(
                [
                    "--probe-results", _probe_dir(tmp_path, {"tpu-0": ok}, tag),
                    "--history", str(tmp_path),  # a DIRECTORY: writes fail
                    "--cordon-after", "2", "--json",
                ]
            )
            return checker.run_check(args, nodes=[json.loads(json.dumps(n)) for n in nodes])

        r1 = run("a", False)
        assert r1.payload["nodes"][0]["health"]["state"] == "SUSPECT"
        r2 = run("b", False)
        assert r2.payload["nodes"][0]["health"]["state"] == "FAILED"
        assert "Cannot append history store" in capsys.readouterr().err

    def test_recovering_to_healthy_alerts_under_slack_on_change(
        self, tmp_path, monkeypatch, capsys
    ):
        # The lift-enabling transition leaves neither the exit code nor the
        # sick set changed (the node left FAILED rounds earlier), yet it
        # must page: actionable transitions are part of the change test.
        from tpu_node_checker import notify as notify_mod

        sent = []
        monkeypatch.setattr(
            notify_mod, "send_slack_message",
            lambda url, message, **kw: sent.append(message) or True,
        )
        monkeypatch.setattr(checker, "_wait_for_next_round", lambda stop, s: False)
        verdicts = [False, True, True, True]  # FAILED → R → R → HEALTHY

        def fake_fetch(args, timer):
            if not verdicts:
                raise KeyboardInterrupt
            ok = verdicts.pop(0)
            # A healthy companion keeps the AGGREGATE exit at 0 throughout:
            # only the hysteresis transition can page.
            d = _probe_dir(
                tmp_path, {"tpu-0": ok, "tpu-1": True}, f"w{len(verdicts)}"
            )
            args.probe_results = d
            return [
                json.loads(json.dumps(_tpu_node())),
                json.loads(json.dumps(_tpu_node("tpu-1"))),
            ], None

        monkeypatch.setattr(checker, "_fetch_nodes", fake_fetch)
        code = cli.main(
            [
                "--watch", "1", "--slack-on-change",
                "--slack-webhook", "https://x",
                "--probe-results", str(tmp_path),
                "--history", str(tmp_path / "h.jsonl"),
                "--uncordon-after", "3",
            ]
        )
        assert code == 130
        # Round 1 (first state + →FAILED), round 2 (tpu-0 leaves the sick
        # set: FAILED→RECOVERING), round 3 silent (RECOVERING wobble is
        # sub-threshold), round 4 pages the re-earned HEALTHY despite an
        # unchanged exit code AND unchanged (empty) sick set.
        assert len(sent) == 3
        assert "→ HEALTHY" in sent[-1]
        capsys.readouterr()

    def test_trend_nodes_survives_malformed_dict_lines(self, tmp_path, capsys):
        # A hand-edited line with a string ts / string flaps_total is a
        # dict (passes the tolerant loader) but must degrade, not crash.
        hist = tmp_path / "h.jsonl"
        hist.write_text(
            json.dumps({"schema": 1, "node": "a", "ts": "oops", "ok": False,
                        "state": "FAILED", "flaps_total": "3"}) + "\n"
            + json.dumps({"schema": 1, "node": "a", "ts": 1_700_000_060.0,
                          "ok": True, "state": "HEALTHY", "streak": 1,
                          "flaps": 0, "flaps_total": 1}) + "\n"
        )
        assert cli.main(["--trend-nodes", str(hist), "--json"]) == 0
        s = json.loads(capsys.readouterr().out)
        assert s["nodes"]["a"]["rounds"] == 2

    def test_flap_window_default_checked_against_small_max_rounds(self, capsys):
        with pytest.raises(SystemExit):
            cli.parse_args(["--history", "h", "--history-max-rounds", "4"])
        assert "cannot exceed --history-max-rounds" in capsys.readouterr().err

    def test_flaps_counter_is_monotonic_across_node_departure(self, tmp_path):
        # flaps_total sums over every node the STORE remembers: a departed
        # flapper's flips must not vanish (Prometheus would read the drop
        # as a counter reset → spurious rate spike on scale-down).
        from tpu_node_checker.checker import _history_payload
        from tpu_node_checker.history import HealthFSM, HistoryStore
        from tpu_node_checker.detect import NodeInfo

        fsm = HealthFSM()
        for v in (False, True, False, True):
            fsm.observe("departed", v)
        flaps = fsm.health("departed").flaps_total
        assert flaps == 3
        survivor = NodeInfo(name="alive", ready=True, accelerators=4,
                            breakdown={}, families=("tpu",), labels={},
                            taints=[])
        fsm.observe("alive", True)
        payload = _history_payload(
            {"fsm": fsm, "store": HistoryStore(str(tmp_path / "h"))},
            [survivor],
        )
        assert payload["flaps_total"] == flaps  # departed node still counted
        assert payload["states"]["HEALTHY"] == 1  # gauges: fleet-only


class TestHistorySurfaces:
    def test_metrics_families(self, tmp_path):
        from tpu_node_checker.metrics import render_metrics

        result = checker.CheckResult(
            exit_code=0,
            payload={
                "total_nodes": 2,
                "ready_nodes": 1,
                "nodes": [],
                "slices": [],
                "history": {
                    "states": {"HEALTHY": 1, "CHRONIC": 1},
                    "chronic": ["tpu-1"],
                    "flaps_total": 7,
                    "transitions": [],
                },
            },
        )
        text = render_metrics(result)
        assert 'tpu_node_checker_node_state{state="HEALTHY"} 1.0' in text
        assert 'tpu_node_checker_node_state{state="CHRONIC"} 1.0' in text
        # Every state emits, 0 included — recovery is a return to zero.
        assert 'tpu_node_checker_node_state{state="SUSPECT"} 0.0' in text
        assert "tpu_node_checker_node_flaps_total 7.0" in text

    def test_no_history_no_families(self):
        from tpu_node_checker.metrics import render_metrics

        result = checker.CheckResult(
            exit_code=0,
            payload={"total_nodes": 1, "ready_nodes": 1, "nodes": [], "slices": []},
        )
        text = render_metrics(result)
        assert "tpu_node_checker_node_state" not in text
        assert "tpu_node_checker_node_flaps_total" not in text

    def test_slack_only_on_error_pages_on_actionable_transition(self):
        # Exit 0 + a node going CHRONIC must page through
        # --slack-only-on-error: the aggregate code never moves for one
        # flapper in a big fleet.
        assert notify.should_send_slack_message(
            "https://x", True, healthy=True, transitions=True
        )
        assert not notify.should_send_slack_message(
            "https://x", True, healthy=True, transitions=False
        )

    def test_emitter_mode_records_history(self, tmp_path, monkeypatch):
        from tpu_node_checker.probe.liveness import ProbeResult

        emissions = []

        def fake_probe(**kw):
            emissions.append(1)
            sick = len(emissions) % 2 == 1  # alternating: a flapping chip
            return ProbeResult(
                ok=not sick, level="enumerate", hostname="h", elapsed_ms=1.0,
                device_count=8, error="dead" if sick else None,
            )

        monkeypatch.setattr("tpu_node_checker.probe.run_local_probe", fake_probe)
        monkeypatch.setattr(
            checker, "_wait_for_next_round", lambda stop, s: len(emissions) >= 6
        )
        out = tmp_path / "h.json"
        log = tmp_path / "rounds.jsonl"
        hist = tmp_path / "history.jsonl"
        code = cli.main([
            "--emit-probe", str(out), "--watch", "1",
            "--history", str(hist), "--log-jsonl", str(log),
        ])
        assert code == 143
        entries = [json.loads(x) for x in log.read_text().splitlines()]
        # The emitter's own round log carries the hysteresis state…
        assert [e["state"] for e in entries[:2]] == ["FAILED", "HEALTHY"]
        # …and the flapping chip ends CHRONIC in the store.
        stored = [json.loads(x) for x in hist.read_text().splitlines()]
        assert stored[-1]["node"] == "h"
        assert stored[-1]["state"] == "CHRONIC"

    def test_trend_nodes_view(self, tmp_path, capsys):
        hist = tmp_path / "history.jsonl"
        t0 = 1_700_000_000.0
        lines = []
        # tpu-0: fails rounds 2-3 of 6 (one outage, repaired) …
        for i, ok in enumerate([True, True, False, False, True, True]):
            lines.append({"schema": 1, "node": "tpu-0", "ts": t0 + 60 * i,
                          "ok": ok, "causes": [] if ok else ["probe-failed"],
                          "state": "HEALTHY" if ok else "FAILED",
                          "streak": 1, "flaps": 0, "flaps_total": 0})
        # …tpu-1: a chronic flapper.
        for i, ok in enumerate([False, True, False, True, False, True]):
            lines.append({"schema": 1, "node": "tpu-1", "ts": t0 + 60 * i,
                          "ok": ok, "causes": [] if ok else ["probe-failed"],
                          "state": "CHRONIC", "streak": 0, "flaps": 5,
                          "flaps_total": 5})
        hist.write_text("\n".join(json.dumps(x) for x in lines) + "\n")
        assert cli.main(["--trend-nodes", str(hist), "--json"]) == 0
        s = json.loads(capsys.readouterr().out)
        assert s["chronic"] == ["tpu-1"]
        assert s["worst_offenders"][0] == "tpu-1"  # 50% < 66.67%
        n0 = s["nodes"]["tpu-0"]
        assert n0["availability_pct"] == pytest.approx(66.67, abs=0.01)
        assert n0["failures"] == 1
        assert n0["mttr_s"] == 120.0  # failed at t+120, good again at t+240
        n1 = s["nodes"]["tpu-1"]
        assert n1["failures"] == 3
        assert n1["mtbf_s"] == 120.0  # onsets at t+0, t+120, t+240
        assert n1["top_causes"] == ["probe-failed"]
        # Human rendering: worst offender leads the table.
        assert cli.main(["--trend-nodes", str(hist)]) == 0
        out = capsys.readouterr().out
        assert "chronic flappers: tpu-1" in out
        assert out.index("tpu-1") < out.index("tpu-0  ")

    def test_trend_nodes_empty_and_unreadable_are_machine_readable(
        self, tmp_path, capsys
    ):
        empty = tmp_path / "empty.jsonl"
        empty.write_text("\n  \n")
        assert cli.main(["--trend-nodes", str(empty), "--json"]) == 1
        captured = capsys.readouterr()
        assert json.loads(captured.out)["nodes"] == {}
        assert "Traceback" not in captured.err
        assert cli.main(["--trend-nodes", str(tmp_path / "absent"), "--json"]) == 1
        assert "error" in json.loads(capsys.readouterr().out)

    def test_trend_surfaces_chronic_from_round_log(self, tmp_path, capsys):
        # --history rounds record standing chronic flappers in the trend
        # log even on exit-0 rounds; --trend must surface the current set.
        log = tmp_path / "trend.jsonl"
        log.write_text(
            json.dumps({"ts": 1_700_000_000, "exit_code": 0}) + "\n"
            + json.dumps({"ts": 1_700_000_060, "exit_code": 0,
                          "chronic": ["tpu-3"]}) + "\n"
        )
        assert cli.main(["--trend", str(log), "--json"]) == 0
        s = json.loads(capsys.readouterr().out)
        assert s["chronic_nodes"] == ["tpu-3"]
        assert cli.main(["--trend", str(log)]) == 0
        assert "chronic flappers held in quarantine: tpu-3" in (
            capsys.readouterr().out
        )

    def test_state_log_records_chronic_on_exit0_rounds(
        self, tmp_path, monkeypatch
    ):
        # The state-log side of the same contract: chronic rides the entry
        # even when the round grades 0 (causes only exist on bad rounds).
        log = tmp_path / "t.jsonl"
        args = cli.parse_args(["--log-jsonl", str(log)])
        result = checker.CheckResult(
            exit_code=0,
            payload={
                "total_nodes": 2, "ready_nodes": 1, "total_chips": 8,
                "ready_chips": 4, "slices": [],
                "history": {"chronic": ["tpu-1"]},
            },
        )
        checker._append_state_log(args, result)
        (entry,) = [json.loads(x) for x in log.read_text().splitlines()]
        assert entry["exit_code"] == 0
        assert entry["chronic"] == ["tpu-1"]

    def test_trend_nodes_runs_alone(self, capsys):
        with pytest.raises(SystemExit):
            cli.parse_args(["--trend-nodes", "f", "--probe"])
        assert "--trend-nodes runs alone" in capsys.readouterr().err


class TestHistoryCli:
    @pytest.mark.parametrize(
        "argv, fragment",
        [
            (["--cordon-after", "2"], "requires --history"),
            (["--uncordon-after", "2"], "requires --history"),
            (["--flap-threshold", "4"], "requires --history"),
            (["--flap-window", "10"], "requires --history"),
            (["--history-max-rounds", "8"], "requires --history"),
            (["--history", "h", "--cordon-after", "0"], "at least 1"),
            (["--history", "h", "--flap-threshold", "1"], "at least 2"),
            (["--history", "h", "--flap-window", "1"], "at least 2"),
            (
                ["--history", "h", "--flap-window", "8",
                 "--history-max-rounds", "4"],
                "cannot exceed --history-max-rounds",
            ),
            (["--trend", "t", "--history", "h"], "--trend runs alone"),
        ],
    )
    def test_flag_validation(self, argv, fragment, capsys):
        with pytest.raises(SystemExit):
            cli.parse_args(argv)
        assert fragment in capsys.readouterr().err
