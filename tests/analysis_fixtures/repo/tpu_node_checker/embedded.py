"""Seed for the engine's virtual-file extraction: a ``*_SCRIPT`` string
constant is production code and gets linted like any module, with findings
and suppressions landing on THIS file's line numbers."""

CHILD_SCRIPT = r"""
import json

out = {}
try:
    out["ok"] = True
except Exception:  # EXPECT[TNC010]
    out["ok"] = False
try:
    out["graded"] = 1
except Exception:  # tnc: allow-broad-except(seed: child reports, never raises)
    out["graded"] = 0
print(json.dumps(out))
"""

NOT_PYTHON_SCRIPT = """
this is a shell-ish template, $NOT python — the walker must skip it
"""
