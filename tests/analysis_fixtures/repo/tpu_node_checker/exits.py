"""Seeds for TNC015 (exit-code): symbolic constants only, outside cli.py."""

import sys

EXIT_ERROR = 1


def hard_exit():
    sys.exit(3)  # EXPECT[TNC015]


def raise_exit():
    raise SystemExit(2)  # EXPECT[TNC015]


def symbolic_exit():  # near-miss: the documented contract, by name
    sys.exit(EXIT_ERROR)


def message_exit():  # near-miss: exiting with a message is not a code
    sys.exit("refusing: bad arguments")
