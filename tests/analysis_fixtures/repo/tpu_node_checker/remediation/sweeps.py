"""Seeds for TNC019's call-site half: actuator calls outside the
sanctioned actuate module are findings wherever they hide."""


def rogue_sweep(client, nodes):
    for n in nodes:
        client.cordon_node(n)  # EXPECT[TNC019]


def rogue_lift(client, name):
    client.uncordon_node(name)  # EXPECT[TNC019]


def plan_cordon_nodes(nodes):  # near-miss: suffix differs (plural), no call
    return [n for n in nodes if n.startswith("gke-")]


def gated_sweep(client, decisions, actuate, events):
    # near-miss: routed through the actuate module — the sanctioned shape.
    for decision in decisions:
        actuate.cordon(client, decision, events=events)
