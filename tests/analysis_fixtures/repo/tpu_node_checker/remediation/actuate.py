"""Seeds for TNC019's sanctioned-module half: actuating functions here
must take the budget ``decision`` and emit an audit event."""


def cordon(client, decision, events):  # near-miss: decision + emit, clean
    client.cordon_node(decision.node)
    events.emit("remediation-cordon", node=decision.node)


def cordon_unproven(client, events):  # EXPECT[TNC019]
    client.cordon_node("gke-tpu-0")
    events.emit("remediation-cordon", node="gke-tpu-0")


def evict_silent(client, decision, namespace, pod):  # EXPECT[TNC019]
    client.evict_pod(namespace, pod)


def plan_only(decision, events):  # near-miss: no actuator call at all
    events.emit("remediation-planned", node=decision.node)
