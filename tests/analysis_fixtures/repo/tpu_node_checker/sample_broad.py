"""Seeds for TNC010 (broad-except) and the suppression meta rules."""


def swallow_everything():
    try:
        return 1
    except Exception:  # EXPECT[TNC010]
        return None


def swallow_bare():
    try:
        return 1
    except:  # noqa: E722  # EXPECT[TNC010]
        return None


def rethrows():  # near-miss: broad, but the error still surfaces
    try:
        return 1
    except Exception as exc:
        raise RuntimeError("wrapped") from exc


def narrow():  # near-miss: a specific type is the whole point of the rule
    try:
        return 1
    except ValueError:
        return None


def sanctioned():  # suppressed with a reason: counted, not a finding
    try:
        return 1
    except Exception:  # tnc: allow-broad-except(seed: a probe-style report-never-raise site)
        return None


def no_reason_given():
    try:
        return 1
    # A reasonless waiver is itself a finding AND does not suppress:
    # both TNC002 (the empty parens) and TNC010 (still unsuppressed) fire.
    except Exception:  # tnc: allow-broad-except()  # EXPECT[TNC002] EXPECT[TNC010]
        return None


def unknown_rule_named():
    try:
        return 1
    except Exception:  # tnc: allow-everything-forever(why not)  # EXPECT[TNC003] EXPECT[TNC010]
        return None
