"""Seeds for the typestate tier: TNC114 (exception-escape), TNC115
(must-release), TNC117 (finally-hygiene) — one positive and the nearest
near-miss for every shape the interpreter distinguishes."""

import socket
import threading

_DEATHS: list = []


# -- TNC114: a thread entry whose escape set is non-empty ------------------

def doomed_worker():  # EXPECT[TNC114]
    raise RuntimeError("boom")


def spawn_doomed():
    threading.Thread(target=doomed_worker, name="tnc-doomed",
                     daemon=True).start()


def recorded_worker():  # near-miss: the death is caught and recorded
    try:
        raise RuntimeError("boom")
    except RuntimeError as exc:
        _DEATHS.append(str(exc))


def spawn_recorded():
    threading.Thread(target=recorded_worker, name="tnc-recorded",
                     daemon=True).start()


# -- TNC115: acquire without release on some path --------------------------

def leaky_socket(addr):
    s = socket.socket()  # EXPECT[TNC115]
    s.connect(addr)


def may_fail(addr):
    if not addr:
        raise ValueError("no address")


def exception_path_leak(addr):
    s = socket.socket()  # EXPECT[TNC115]
    may_fail(addr)  # raises past the close below: the accept-loop shape
    s.close()


def exception_safe(addr):  # near-miss: finally releases on every path
    s = socket.socket()
    try:
        may_fail(addr)
    finally:
        s.close()


def managed_socket(addr):  # near-miss: __exit__ releases on every path
    with socket.socket() as s:
        s.connect(addr)


class Holder:
    def adopt(self):  # near-miss: stored into self — obligation moves
        self.sock = socket.socket()

    def close(self):
        self.sock.close()


def close_it(s):
    s.close()


def handoff():  # near-miss: the callee's summary says it releases arg 0
    s = socket.socket()
    close_it(s)


def minted():  # near-miss: returned — the caller owns it now
    s = socket.socket()
    return s


def sanctioned_probe(addr):
    # tnc: allow-must-release(standalone account: waiver kept while the probe API settles)
    s = socket.socket()  # tnc: allow-must-release(probe socket hands its fd to the harness, which closes it)
    s.connect(addr)


# -- TNC117: release reachable only on the fall-through path ---------------

def early_return_skips_close(path, flag):
    fh = open(path, "rb")
    if flag:
        return None  # EXPECT[TNC117]
    data = fh.read()
    fh.close()
    return data


def finally_closed(path, flag):  # near-miss: finally runs on every exit
    fh = open(path, "rb")
    try:
        if flag:
            return None
        return fh.read()
    finally:
        fh.close()
