"""Seeds for TNC102 on the delta-publish shape: a delta build may READ the
live snapshot freely, but once the new one is swapped in it never mutates —
request threads hold references to it."""


class DeltaPublisher:
    def __init__(self):
        self._snap = None

    def publish_delta_then_patch(self, payload, changed):
        prev = self._snap
        snap = {"entities": {}, "fragments": {}}
        for name in changed:
            snap["fragments"][name] = payload[name]  # near-miss: pre-swap
        if prev is not None:
            snap["entities"].update(prev["entities"])  # near-miss: reads prev, mutates the private build
        self._snap = snap
        snap["fragments"]["late-node"] = payload  # EXPECT[TNC102]
        return snap

    def publish_delta_clean(self, payload, changed):
        snap = {"entities": {}, "fragments": {k: payload[k] for k in changed}}
        snap["seq"] = 1
        self._snap = snap
        return snap
