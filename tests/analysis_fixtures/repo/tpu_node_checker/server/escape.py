"""Seeds for TNC113 (snapshot-escape): the publish path's freeze as
DATAFLOW.  None of these are direct post-swap mutations of the published
name (that is TNC102, seeded in pub.py/deltapub.py) — they leak the
snapshot's mutable internals, or mutate what BUILT it, after the swap."""

from tpu_node_checker.flowpkg.mutators import count_entities, stamp_late


class Snap:
    def __init__(self):
        self.entities = {}


class EscapePublisher:
    def __init__(self):
        self._snap = None
        self._hot = None

    def publish_store_internals(self, payload):
        snap = {"entities": dict(payload)}
        self._snap = snap
        self._hot = snap["entities"]  # EXPECT[TNC113]

    def publish_feed_mutation(self, payload):
        entities = dict(payload)
        snap = {"entities": entities}
        self._snap = snap
        entities["late"] = payload  # EXPECT[TNC113]

    def publish_return_internals(self, payload):
        snap = {"fragments": dict(payload)}
        self._snap = snap
        return snap["fragments"]  # EXPECT[TNC113]

    def publish_pass_to_mutator(self, payload):
        snap = Snap()
        snap.entities.update(payload)
        self._snap = snap
        stamp_late(snap)  # EXPECT[TNC113]

    def publish_clean_reader(self, payload):
        # near-misses: build-then-swap, return the HANDLE (not an
        # internal), and a callee that only reads its parameter.
        snap = Snap()
        snap.entities.update(payload)
        self._snap = snap
        count_entities(snap)
        return snap
