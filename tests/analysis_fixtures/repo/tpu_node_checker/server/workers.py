"""Seeds for TNC011 on the worker-pool shape: the accept-loop READ path
(fast responders, header extraction) takes no locks — a lock there
serializes every worker — while accept-side bookkeeping (connection
registry, shed guard) legitimately may."""

import threading


class AcceptWorker:
    def __init__(self):
        self._lock = threading.Lock()
        self._routes = {}
        self._accepted = 0

    def _respond_fast(self, line, out):
        with self._lock:  # EXPECT[TNC011]
            route = self._routes.get(line)
        if route is not None:
            out += route
        return route

    def _get_route(self, line):
        return self._routes.get(line)  # near-miss: lock-free read path

    def _count_accept(self, conn):  # near-miss: accept bookkeeping, not the read path
        with self._lock:
            self._accepted += 1
        return conn
