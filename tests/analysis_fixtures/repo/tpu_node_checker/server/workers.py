"""Seeds for TNC011 on the worker-pool shape: the accept-loop READ path
(fast responders, header extraction) takes no locks — a lock there
serializes every worker — while accept-side bookkeeping (connection
registry, shed guard) legitimately may.  TNC111 seeds ride on the same
roots: the blocking call hides in ANOTHER module (storeio.py), visible
only to the call-graph rule, and lands on the root's ``def`` line."""

import threading

from tpu_node_checker.storeio import deep_fetch, fetch_snapshot, shape_route


class AcceptWorker:
    def __init__(self):
        self._lock = threading.Lock()
        self._routes = {}
        self._accepted = 0

    def _respond_fast(self, line, out):
        with self._lock:  # EXPECT[TNC011]
            route = self._routes.get(line)
        if route is not None:
            out += route
        return route

    def _get_route(self, line):
        return self._routes.get(line)  # near-miss: lock-free read path

    def _count_accept(self, conn):  # near-miss: accept bookkeeping, not the read path
        with self._lock:
            self._accepted += 1
        return conn

    def _get_cached(self, line):  # EXPECT[TNC111]
        return fetch_snapshot(self._routes.get(line))

    def _get_deep(self, line):  # EXPECT[TNC111]
        return deep_fetch(self._routes.get(line))  # blocking two calls down

    def _get_shaped(self, line):  # near-miss: the whole callee chain is pure
        return shape_route(self._routes.get(line, ""))

    # tnc: allow-transitive-blocking(seed: sanctioned root — the waiver on the root covers the callee-file blocking site)
    def _get_waived(self, line):
        return fetch_snapshot(self._routes.get(line))
