"""Seeds for TNC011 (blocking-read-path): snapshot.py read vs build side."""

import threading
import time

_lock = threading.Lock()


def current_entity(key):
    time.sleep(0.01)  # EXPECT[TNC011]
    with _lock:  # EXPECT[TNC011]
        return key


def lookup(snapshots, key):  # near-miss: a dict lookup is the whole contract
    return snapshots.get(key)


def build_snapshot(path):  # near-miss: builders run off the request path
    with open(path) as fh:
        return fh.read()


def json_entity(obj):  # near-miss: named builder helper
    with open("/dev/null", "w") as fh:
        fh.write(str(obj))
    return obj
