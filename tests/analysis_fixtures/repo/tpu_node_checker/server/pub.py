"""Seeds for TNC102 (snapshot-mutation): build fully, then swap."""


class Publisher:
    def __init__(self):
        self._snap = None

    def publish_then_mutate(self, payload):
        snap = {"entities": {}}
        snap["entities"]["summary"] = payload  # near-miss: still private
        self._snap = snap
        snap["entities"]["late"] = payload  # EXPECT[TNC102]
        snap["entities"].update(extra=1)  # EXPECT[TNC102]
        return snap

    def publish_clean(self, payload):  # near-miss: all mutation pre-swap
        snap = {"entities": {"summary": payload}}
        snap["seq"] = 1
        self._snap = snap
        return snap
