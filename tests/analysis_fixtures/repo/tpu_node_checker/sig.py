"""Seeds for TNC012 (signal-handler-blocking)."""

import signal
import threading
import time

_STOP = threading.Event()


def _blocking_handler(signum, frame):
    time.sleep(1.0)  # EXPECT[TNC012]
    with open("/tmp/x", "w") as fh:  # EXPECT[TNC012]
        fh.write("bye")


def _clean_handler(signum, frame):  # near-miss: flag-flip only
    _STOP.set()


def _unregistered_helper():  # near-miss: sleeps, but never a signal handler
    time.sleep(0.5)


def install():
    signal.signal(signal.SIGTERM, _blocking_handler)
    signal.signal(signal.SIGINT, _clean_handler)
