"""Seeds for TNC011 on the federation merge shape: the merged-snapshot
READ path (GlobalSnapshot's entity accessors, what every
/api/v1/global/* GET rides) takes no locks, while the merge builders —
run once per round, after the fetch workers joined — legitimately may."""

import threading


class GlobalView:
    def __init__(self):
        self._lock = threading.Lock()
        self._entities = {}
        self._cluster_entities = {}

    def cluster_entity(self, name):
        with self._lock:  # EXPECT[TNC011]
            return self._cluster_entities.get(name)

    def entity(self, key):
        return self._entities[key]  # near-miss: lock-free read path

    def build_global(self, views):  # near-miss: builder, off the read path
        with self._lock:
            merged = {v["name"]: v for v in views}
        self._entities = {"global/summary": merged}
        return merged
