"""Seeds for the stream-consumer (feed-reader) thread shape the
streaming-federation tier introduced: a daemon thread long-polls an
upstream watch feed and folds frames into state the engine's round
thread drains.  The spawn makes the reader its OWN lock domain while
``seed_view`` keeps the main path writing too, so TNC112 must judge
every ``FeedTable`` write across BOTH domains: the locked fold in
``feedstate.py`` is the near-miss (quiet), the bare cursor reset below
— the tempting "just drop the cursor on error" move — is the race."""

import threading

from tpu_node_checker.federation.feedstate import FeedTable


def start_reader(table: 'FeedTable'):
    threading.Thread(
        target=_consume, args=(table,), name="tnc-feed-reader", daemon=True
    ).start()


def seed_view(table: 'FeedTable', entries):
    # main-path writer: the same locked fold the reader uses, so the
    # table genuinely spans two thread domains.
    table.apply(entries)


def _consume(table: 'FeedTable'):
    table.apply({"tpu-00": {"ready": True}})  # near-miss: the locked fold
    table.cursor = ""  # EXPECT[TNC112]


def peek(table: 'FeedTable'):
    return table.cursor  # near-miss: a read is not a write-write race
