"""Shared-state side of the feed-reader seeds (TNC112): a cursor + entry
table guarded by one lock, folded from a consumer thread in
``feedreader.py``.  ``apply`` is the clean shape — every write under the
lock — that the reader-side bare write races against."""

import threading


class FeedTable:
    def __init__(self):
        self._lock = threading.Lock()
        self.cursor = ""
        self.entries = {}

    def apply(self, frame):
        with self._lock:
            self.entries.update(frame)
            self.cursor = "verified"
