"""Seeds for TNC101 (unlocked-write)."""

import threading


class Guarded:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0  # near-miss: __init__ constructs, no peer threads yet
        self.label = ""

    def bump(self):
        with self._lock:
            self.count += 1

    def rename(self, label):
        with self._lock:
            self.label = label

    def reset_racy(self):
        self.count = 0  # EXPECT[TNC101]

    def clear_label_racy(self):
        self.label = ""  # EXPECT[TNC101]

    def sanctioned_reset(self):
        # tnc: allow-unlocked-write(seed: single-threaded teardown path, peers already joined)
        self.count = 0


class Unguarded:  # near-miss: no lock anywhere → rule stays silent
    def __init__(self):
        self.count = 0

    def bump(self):
        self.count += 1
