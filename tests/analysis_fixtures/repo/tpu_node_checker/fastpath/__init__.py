"""TNC018 corpus twin of the fastpath package."""
