"""TNC018 corpus: the sanctioned oracle vs a sneaky second decode site."""

import json


def oracle_decode_page(resp):
    # Near miss: THE sanctioned full-body decode — the one site the rule
    # exempts by name.
    doc = json.loads(resp.content)
    return doc.get("items") or [], doc.get("metadata") or {}


def decode_page_quickly(resp):
    return json.loads(resp.content)  # EXPECT[TNC018]
