"""Callee-side seeds for TNC111: blocking work that is invisible to the
per-file TNC011 scan because it sits in ANOTHER module, one or two calls
below a read-path root in server/workers.py."""

import time


def fetch_snapshot(pool):
    time.sleep(0.01)  # the blocking site TNC111 must trace to its root
    return pool


def deep_fetch(pool):
    return fetch_snapshot(pool)  # depth 2: the ban follows calls


def shape_route(route):  # near-miss: pure compute, nothing blocking
    return [route, len(route)]
