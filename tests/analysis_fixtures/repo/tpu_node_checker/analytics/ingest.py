"""Seeds for TNC021's call-site half: raw segment writes outside
segments.py are findings; the append_bucket gate is the sanctioned path."""

import json

from tpu_node_checker.analytics import segments


def rogue_flush(path, records):
    lines = [json.dumps(r) for r in records]
    segments.rollup_append_lines(path, lines)  # EXPECT[TNC021]


def rogue_compact(path, records):
    segments.rollup_replace_file(  # EXPECT[TNC021]
        path, [json.dumps(r) for r in records]
    )


def gated_flush(path, records):  # near-miss: through the gate
    segments.append_bucket(path, records)


def append_bucket_counts(counts):  # near-miss: suffix differs, no call
    return sum(counts.values())


def rogue_sketch_write(path, sk):
    from tpu_node_checker.analytics import sketch

    doc = sketch.sketch_state(sk)  # EXPECT[TNC021]
    segments.append_bucket(path, [{"sk": doc}])


def rogue_sketch_read(rec):
    from tpu_node_checker.analytics.sketch import sketch_from_state

    return sketch_from_state(rec.get("sk"))  # EXPECT[TNC021]


def merged_block(docs):  # near-miss: the free read/merge surface
    from tpu_node_checker.analytics.sketch import merge_state_docs

    return merge_state_docs(docs)


def export_sketch(sk):  # near-miss: wire shape, not persistence
    return sk.to_doc()
