"""Seeds for TNC021's call-site half: raw segment writes outside
segments.py are findings; the append_bucket gate is the sanctioned path."""

import json

from tpu_node_checker.analytics import segments


def rogue_flush(path, records):
    lines = [json.dumps(r) for r in records]
    segments.rollup_append_lines(path, lines)  # EXPECT[TNC021]


def rogue_compact(path, records):
    segments.rollup_replace_file(  # EXPECT[TNC021]
        path, [json.dumps(r) for r in records]
    )


def gated_flush(path, records):  # near-miss: through the gate
    segments.append_bucket(path, records)


def append_bucket_counts(counts):  # near-miss: suffix differs, no call
    return sum(counts.values())
