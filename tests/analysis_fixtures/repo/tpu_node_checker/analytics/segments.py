"""Seeds for TNC021's sanctioned-module half: functions here that touch
the raw segment I/O must prove their lines carry the schema major."""

import json

ROLLUP_SCHEMA_VERSION = 1


def rollup_append_lines(path, lines):  # the primitive itself: exempt
    with open(path, "a", encoding="utf-8") as f:
        for line in lines:
            f.write(line + "\n")


def rollup_replace_file(path, lines):  # the primitive itself: exempt
    with open(path + ".tmp", "w", encoding="utf-8") as f:
        for line in lines:
            f.write(line + "\n")


def stamp_bucket(record):
    return {"schema": ROLLUP_SCHEMA_VERSION, **record}


def append_bucket(path, records):  # near-miss: stamps through the helper
    rollup_append_lines(
        path, [json.dumps(stamp_bucket(r)) for r in records]
    )


def compact(path, records):  # near-miss: filters by the schema constant
    keep = [r for r in records if r.get("schema") == ROLLUP_SCHEMA_VERSION]
    rollup_replace_file(path, [json.dumps(r) for r in keep])


def append_unstamped(path, records):  # EXPECT[TNC021]
    rollup_append_lines(path, [json.dumps(r) for r in records])
