"""Seeds for TNC013 (mutable-default)."""


def literal_list(items=[]):  # EXPECT[TNC013]
    return items


def constructor_dict(cache=dict()):  # EXPECT[TNC013]
    return cache


def keyword_only_set(*, seen={1}):  # EXPECT[TNC013]
    return seen


def none_sentinel(items=None):  # near-miss: the correct idiom
    return items or []


def immutable_tuple(dims=(2, 2)):  # near-miss: immutable defaults are fine
    return dims
