"""Seeds for TNC017: observability discipline — spans close via ``with``
(a bare ``start_span`` is never closed and corrupts every offset after
it); ``HistogramFamily`` names carry a unit suffix (``_ms`` or ``_us``)
and declare their buckets."""

BUCKETS_MS = (1.0, 5.0, 25.0)


def traced_round(tracer):
    with tracer.span("fold"):  # near-miss: the sanctioned with-closed span
        pass
    with tracer.start_span("grade"):  # near-miss: a with-context still closes
        pass
    span = tracer.start_span("merge")  # EXPECT[TNC017]
    span.end()
    tracer.restart_span("merge")  # near-miss: suffix match must be exact


def histogram_families(HistogramFamily):
    ok = HistogramFamily(
        "tpu_node_checker_round_phase_duration_ms",  # near-miss: _ms, buckets
        "per-phase round cost",
        BUCKETS_MS,
        label="phase",
    )
    ok_kw = HistogramFamily(
        "tpu_node_checker_api_wait_ms",  # near-miss: buckets via keyword
        "request wait",
        buckets=BUCKETS_MS,
    )
    ok_us = HistogramFamily(
        "tpu_node_checker_mesh_link_duration_us",  # near-miss: _us is a unit
        "per-link ICI sweep timing",
        BUCKETS_MS,
        label=("slice", "axis"),
    )
    bad_name = HistogramFamily(
        "tpu_node_checker_fetch_duration_seconds",  # EXPECT[TNC017]
        "seconds-denominated family",
        BUCKETS_MS,
    )
    bad_buckets = HistogramFamily(  # EXPECT[TNC017]
        "tpu_node_checker_publish_duration_ms",
        "no buckets declared",
    )
    return ok, ok_kw, ok_us, bad_name, bad_buckets
