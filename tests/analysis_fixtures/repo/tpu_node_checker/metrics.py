"""Seeds for TNC014 (metric-name) and the TNC202 reverse direction.

Documented families (this docstring is the fixture's metric index):

* ``tpu_node_checker_doc_gauge`` — documented here, emitted below: clean;
* ``tpu_node_checker_stats_requests_total`` — the hand-built TYPE-line form.
"""


def _line(name, value, labels=None):
    return f"{name} {value}"


def family(name, mtype, help_text, samples):
    return [name, mtype, help_text, samples]


def render():
    out = []
    out += family("tpu_node_checker_doc_gauge", "gauge", "documented", [({}, 1.0)])
    out += family("tpu_node_checker_readme_gauge", "gauge", "in README", [({}, 1.0)])
    out += family("bad_metric_name", "gauge", "wrong namespace", [({}, 1.0)])  # EXPECT[TNC014]
    out += family("tpu_node_checker_bad_counter", "counter", "no _total", [({}, 1.0)])  # EXPECT[TNC014]
    out += family("tpu_node_checker_ghosted_gauge", "gauge", "undocumented", [({}, 1.0)])  # EXPECT[TNC202]
    out.append(_line("tpu_node_checker_doc_gauge", 1.0))
    out.append(
        "# TYPE tpu_node_checker_stats_requests_total counter"  # near-miss: well-formed TYPE line
    )
    out.append("# TYPE tpu_node_checker_stats_inflight counter")  # EXPECT[TNC014] EXPECT[TNC202]
    return out
