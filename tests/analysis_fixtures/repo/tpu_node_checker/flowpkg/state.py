"""Seeds for TNC112 (lockset-race): lock-guarded state whose OTHER write
sites live in another module — invisible to the per-file TNC101, visible
to the whole-program lock-set rule.  ``_bump_unsafe`` is the inherited-
lockset near-miss: lexically unguarded, but its only caller holds the
lock, so the call-graph meet rescues it."""

import threading


class SharedState:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0

    def bump(self):
        with self._lock:
            self.count += 1

    def locked_helper_call(self):
        with self._lock:
            _bump_unsafe(self)


def _bump_unsafe(state: "SharedState"):
    # near-miss: every resolved caller holds SharedState._lock, so the
    # inherited lock-set is non-empty — no finding.
    state.count += 1


class QuietState:
    """Near-miss: same cross-file write shape, but no thread entry ever
    reaches it — single-domain state needs no lock consistency."""

    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0

    def tally(self):
        with self._lock:
            self.total += 1
