"""Cross-module mutators for the TNC112 seeds: TNC101 cannot see these
(wrong file, wrong receiver spelling), the lock-set rule must."""

from tpu_node_checker.flowpkg.state import QuietState, SharedState


def reset_racy(state: "SharedState"):
    state.count = 0  # EXPECT[TNC112]


def reset_locked(state: "SharedState"):  # near-miss: takes the object's lock
    with state._lock:
        state.count = 0


def quiet_reset(state: "QuietState"):
    state.total = 0  # near-miss: QuietState is reachable from one domain only
