"""Thread entries for the TNC112 seeds: the worker thread and the main
path both reach SharedState, so its writes span two domains."""

import threading

from tpu_node_checker.flowpkg import helper
from tpu_node_checker.flowpkg.state import SharedState


def start_worker(state: "SharedState"):
    thread = threading.Thread(
        target=_worker_loop, args=(state,),
        name="flow-seed-worker", daemon=True,
    )
    thread.start()
    return thread


def _worker_loop(state: "SharedState"):
    helper.reset_racy(state)
    helper.reset_locked(state)


def main_path(state: "SharedState", quiet):
    state.bump()
    state.locked_helper_call()
    helper.quiet_reset(quiet)
