"""Callees for the TNC113 seeds: one mutates its parameter (the escape
the publish path must not hand the snapshot to), one only reads it."""

from tpu_node_checker.server.escape import Snap


def stamp_late(snap: "Snap"):
    snap.entities["stamped"] = True


def count_entities(snap: "Snap"):  # near-miss: read-only callee
    return len(snap.entities)
