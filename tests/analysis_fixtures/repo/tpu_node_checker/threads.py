"""Seeds for TNC103 (thread-hygiene)."""

import threading
from concurrent.futures import ThreadPoolExecutor


def nameless():
    threading.Thread(target=print, daemon=True).start()  # EXPECT[TNC103]


def daemonless():
    threading.Thread(target=print, name="tnc-seed").start()  # EXPECT[TNC103]


def anonymous_pool():
    with ThreadPoolExecutor(max_workers=2) as pool:  # EXPECT[TNC103]
        pool.submit(print)


def hygienic():  # near-miss: both kwargs present
    threading.Thread(target=print, name="tnc-seed-clean", daemon=True).start()


def hygienic_pool():  # near-miss
    with ThreadPoolExecutor(max_workers=2, thread_name_prefix="tnc-seed") as pool:
        pool.submit(print)
