"""Seeds for TNC101 on the watch-stream cache shape: a reader thread and
the tick share per-node state, so every post-construction mutation of the
lock-guarded maps must hold the lock."""

import threading


class EventCache:
    def __init__(self):
        self._lock = threading.Lock()
        self._nodes = {}  # near-miss: __init__ constructs, no reader yet
        self._changed = set()
        self.resource_version = None

    def apply(self, name, obj, rv):
        with self._lock:
            self._nodes[name] = obj
            self._changed.add(name)
            self.resource_version = rv

    def drain(self):
        with self._lock:
            changed = self._changed
            self._changed = set()
            return changed

    def fast_bookmark(self, rv):
        self.resource_version = rv  # EXPECT[TNC101]

    def reseed_racy(self, nodes):
        self._nodes = dict(nodes)  # EXPECT[TNC101]
        self._changed = set(nodes)  # EXPECT[TNC101]

    def local_view(self):  # near-miss: a local name, not shared state
        nodes = {}
        nodes["a"] = 1
        return nodes
