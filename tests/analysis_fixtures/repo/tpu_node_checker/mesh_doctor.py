"""Meshprobe seed for TNC103: the link doctor's hop-deadline watchdog is
a thread, and an unnamed one is exactly the kind that shows up as
``Thread-7`` in a stuck-sweep stack dump with no way to tell which hop it
was guarding."""

import threading


def watchdog_unnamed(deadline_s):
    # The classic drift: daemon-ness chosen, attribution forgotten.
    t = threading.Thread(target=threading.Event().wait, daemon=True)  # EXPECT[TNC103]
    t.start()
    return t


def watchdog_hygienic(axis, hop, deadline_s):
    # near-miss: the approved idiom — the guarded link IS the thread name.
    t = threading.Thread(
        target=threading.Event().wait,
        name=f"tnc-mesh-watchdog-{axis}-{hop}",
        daemon=True,
    )
    t.start()
    return t
