"""Seeds for TNC203 (drift-readme-flags) and the TNC015 cli.py carve-out."""

import argparse
import sys


def parse_args(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--good-flag", action="store_true", help="documented in README")
    p.add_argument("--undocumented-flag", action="store_true", help="nowhere in README")  # EXPECT[TNC203]
    return p.parse_args(argv)


def usage_error():
    sys.exit(2)  # near-miss: bare codes are cli.py's privilege
