"""TNC018 corpus: full-body decodes on the LIST hot path vs off it."""

import json


def _paged_list(session, url, params):
    resp = session.get(url, params=params)
    doc = resp.json()  # EXPECT[TNC018]
    return doc.get("items") or []


def list_nodes(session, url):
    body = session.get(url).content
    return json.loads(body)  # EXPECT[TNC018]


def dump_debug_state(path, state):
    # Near miss: a json.loads in cluster.py OUTSIDE the LIST walk (a debug
    # helper, config parsing, identity probing) is not hot-path work.
    with open(path) as f:
        return json.loads(f.read())
