"""Seeds for TNC116 (atomic-write): this module reads through a torn-
tolerant loader, so every truncating write it makes must be the
tmp-then-``os.replace`` idiom that keeps those readers honest."""

import json
import os


def read_jsonl_tolerant(path):  # the loader call that marks this module
    out = []
    try:
        with open(path, "rb") as fh:
            for line in fh:
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
    except OSError:
        return out
    return out


def load_rollups(path):
    return read_jsonl_tolerant(path)


def torn_overwrite(path, rows):
    with open(path, "w") as fh:  # EXPECT[TNC116]
        for row in rows:
            fh.write(json.dumps(row) + "\n")


def atomic_overwrite(path, rows):  # near-miss: the sanctioned idiom
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        for row in rows:
            fh.write(json.dumps(row) + "\n")
    os.replace(tmp, path)


def append_only(path, row):  # near-miss: append IS the designed tolerance
    with open(path, "a") as fh:
        fh.write(json.dumps(row) + "\n")
