"""Near-miss: the clock SEAM is the one sim file allowed to read reality
— TNC020 exempts exactly this path."""

import time


def wall_now():
    return time.time()


def real_pace(seconds):
    time.sleep(seconds)
