"""Seeds for TNC020 (sim-determinism): the simulator package draws no
global randomness and reads no wall clock outside the clock seam."""
