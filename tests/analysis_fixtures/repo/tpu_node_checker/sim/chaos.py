"""TNC020 true positives and their nearest tempting negatives."""

import os
import random
import time


def pick_failures(hosts):
    return random.sample(hosts, 2)  # EXPECT[TNC020]


def jitter_schedule():
    random.seed(1234)  # EXPECT[TNC020]
    return random.random()  # EXPECT[TNC020]


def stamp_round(record):
    record["ts"] = time.time()  # EXPECT[TNC020]
    return record


def pace_round():
    time.sleep(0.5)  # EXPECT[TNC020]


def mint_trace_prefix():
    return os.urandom(4).hex()  # EXPECT[TNC020]


def seeded_failures(seed, hosts):
    # near-miss: a SEEDED instance is the sanctioned shape — its methods
    # share names with the module-level global-RNG functions.
    rng = random.Random(seed)
    rng.seed(seed)
    return rng.sample(hosts, 2)


def paced_by_clock(clock, record):
    # near-miss: time flows through the injectable seam object.
    clock.sleep(1.0)
    record["ts"] = clock.now()
    return record
