"""Meshprobe near-miss seed for TNC016: a test that paces a per-link
sweep hop by hop LOOKS like sleep-driven timing, but routed through the
injectable fake clock it never really sleeps — the rule must stay quiet
on every line here."""


def sweep_with_fake_pacing(clock, links):
    # near-miss: clock.sleep is the fake-clock seam, not time.sleep —
    # per-link pacing with zero wall-clock cost.
    timings = {}
    for name, budget_us in links:
        clock.sleep(budget_us / 1e6)
        timings[name] = clock.now
    return timings
