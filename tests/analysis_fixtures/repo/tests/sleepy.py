"""Seeds for TNC016 (test-wall-clock)."""

import datetime
import time


def pacing_sleep():
    time.sleep(0.1)  # EXPECT[TNC016]


def wall_clock_read():
    return datetime.datetime.now()  # EXPECT[TNC016]


def bounded_poll():
    time.sleep(0.05)  # tnc: allow-test-wall-clock(seed: bounded poll on a real kernel socket)


class FakeClock:  # near-miss: a fake clock DEFINES sleep without sleeping
    def __init__(self):
        self.now = 0.0

    def sleep(self, seconds):
        self.now += seconds


def uses_fake(clock):  # near-miss: calling the fake is the approved idiom
    clock.sleep(30.0)
    return clock.now
