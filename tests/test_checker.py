"""Exit-code contract matrix and orchestration tests.

Contract under test (check-gpu-node.py:289-293,327 / README.md:135-142):
0 = ≥1 Ready accelerator node, 2 = none exist, 3 = exist but none Ready,
1 = any error — in both table and ``--json`` modes.
"""

import json

from tests import fixtures as fx
from tpu_node_checker import checker, cli, notify


def args_for(*argv):
    return cli.parse_args(list(argv))


def write_nodes(tmp_path, nodes, name="nodes.json"):
    p = tmp_path / name
    p.write_text(json.dumps(fx.node_list(nodes)))
    return str(p)


class TestExitCodeMatrix:
    def run(self, nodes, *extra):
        return checker.one_shot(args_for(*extra), nodes=nodes)

    def test_exit_0_ready_nodes(self, capsys):
        assert self.run(fx.tpu_v5e_single_host()) == 0
        assert self.run(fx.gpu_pool(2)) == 0
        assert self.run(fx.tpu_v5e_single_host(), "--json") == 0

    def test_exit_2_no_accel_nodes(self, capsys):
        assert self.run(fx.cpu_only_cluster()) == 2
        assert self.run(fx.cpu_only_cluster(), "--json") == 2
        assert self.run([]) == 2

    def test_exit_3_none_ready(self, capsys):
        nodes = fx.gpu_pool(2, ready=False)
        assert self.run(nodes) == 3
        assert self.run(nodes, "--json") == 3

    def test_exit_0_partial_ready(self, capsys):
        # Reference semantics: ANY ready accelerator node → 0.
        assert self.run(fx.mixed_cluster_one_notready()) == 0

    def test_exit_3_strict_slices_incomplete(self, capsys):
        nodes = fx.tpu_v5p_64_slice(not_ready=1)
        assert self.run(nodes) == 0  # default keeps reference semantics
        assert self.run(nodes, "--strict-slices") == 3

    def test_exit_1_error_json(self, tmp_path, capsys):
        code = cli.main(["--json", "--nodes-json", str(tmp_path / "missing.json")])
        assert code == 1
        # Machine-readable error on STDOUT (check-gpu-node.py:321-322).
        out = json.loads(capsys.readouterr().out)
        assert "error" in out

    def test_exit_1_error_table_mode_stderr(self, tmp_path, capsys):
        code = cli.main(["--nodes-json", str(tmp_path / "missing.json")])
        assert code == 1
        captured = capsys.readouterr()
        assert "Error:" in captured.err
        assert captured.out == ""


class TestExpectedChips:
    """--expected-chips: cluster-level capacity assertion (SURVEY §5.6)."""

    def test_met_exits_0(self, capsys):
        nodes = fx.tpu_v5e_256_slice()
        args = args_for("--expected-chips", "256", "--json")
        result = checker.run_check(args, nodes=nodes)
        assert result.exit_code == 0
        assert result.payload["expected_chips"] == 256
        assert result.payload["expected_chips_met"] is True

    def test_short_exits_3(self, capsys):
        # 63/64 hosts Ready → 252 chips: nodes are Ready, fleet is short.
        nodes = fx.tpu_v5e_256_slice(not_ready=1)
        args = args_for("--expected-chips", "256")
        result = checker.run_check(args, nodes=nodes)
        assert result.exit_code == 3
        assert result.payload["expected_chips_met"] is False
        assert checker.one_shot(args, nodes=nodes) == 3
        assert "Expected ≥256" in capsys.readouterr().out

    def test_no_accel_nodes_still_exit_2(self, capsys):
        args = args_for("--expected-chips", "8")
        assert checker.run_check(args, nodes=fx.cpu_only_cluster()).exit_code == 2

    def test_keyed_form_ignores_other_families(self, capsys):
        # 8 TPU chips + 4 GPUs: a TPU-keyed assertion must not count GPUs.
        nodes = fx.tpu_v5e_single_host() + fx.gpu_pool(4)
        ok = checker.run_check(
            args_for("--expected-chips", "google.com/tpu=8"), nodes=nodes
        )
        assert ok.exit_code == 0
        short = checker.run_check(
            args_for("--expected-chips", "google.com/tpu=12"), nodes=nodes
        )
        assert short.exit_code == 3
        assert short.payload["expected_chips_key"] == "google.com/tpu"
        assert short.payload["expected_chips_have"] == 8
        # The unkeyed form counts every family (12 here) — documented behavior.
        assert (
            checker.run_check(args_for("--expected-chips", "12"), nodes=nodes).exit_code
            == 0
        )

    def test_keyed_form_accepts_globs(self, capsys):
        nodes = fx.tpu_v5e_single_host()
        r = checker.run_check(
            args_for("--expected-chips", "*.com/tpu=8"), nodes=nodes
        )
        assert r.exit_code == 0

    def test_absent_flag_leaves_payload_clean(self, capsys):
        result = checker.run_check(args_for(), nodes=fx.gpu_pool(1))
        assert "expected_chips" not in result.payload

    def test_rejects_bad_values(self, capsys):
        import pytest

        for bad in ("0", "-3", "google.com/tpu=", "google.com/tpu=x", "four", "=8", "==8"):
            with pytest.raises(SystemExit):
                args_for("--expected-chips", bad)


class TestJsonOutput:
    def test_payload_shape(self, tmp_path, capsys):
        code = cli.main(
            ["--json", "--nodes-json", write_nodes(tmp_path, fx.tpu_v5e_256_slice())]
        )
        assert code == 0
        payload = json.loads(capsys.readouterr().out)
        assert payload["total_nodes"] == 64
        assert payload["ready_chips"] == 256
        assert payload["slices"][0]["complete"] is True
        assert payload["exit_code"] == 0
        assert "timings_ms" in payload

    def test_table_output(self, tmp_path, capsys):
        code = cli.main(["--nodes-json", write_nodes(tmp_path, fx.tpu_v5p_64_slice())])
        assert code == 0
        out = capsys.readouterr().out
        assert "✅" in out
        assert "SLICE(NODEPOOL)" in out
        assert "64/64" in out

    def test_debug_timings(self, tmp_path, capsys):
        cli.main(["--debug", "--nodes-json", write_nodes(tmp_path, fx.gpu_pool(1))])
        assert "Timings (ms):" in capsys.readouterr().out


class TestTrace:
    def test_trace_file_is_valid_chrome_trace(self, tmp_path, capsys):
        trace = tmp_path / "check.trace.json"
        nodes_file = write_nodes(tmp_path, fx.tpu_v5e_single_host())
        code = cli.main(["--nodes-json", nodes_file, "--trace", str(trace)])
        assert code == 0
        doc = json.loads(trace.read_text())
        events = doc["traceEvents"]
        names = {e["name"] for e in events}
        assert {"list", "detect", "render", "total"} <= names
        for e in events:
            if e["ph"] == "X":
                assert e["ts"] >= 0 and e["dur"] >= 0
        # Spans must nest inside the total.
        total = next(e for e in events if e["name"] == "total")
        for e in events:
            if e["ph"] == "X" and e["name"] != "total":
                assert e["ts"] + e["dur"] <= total["dur"] * 1.05

    def test_unwritable_trace_path_is_not_fatal(self, tmp_path, capsys):
        nodes_file = write_nodes(tmp_path, fx.tpu_v5e_single_host())
        code = cli.main(
            ["--nodes-json", nodes_file, "--trace", str(tmp_path / "no" / "dir" / "t.json")]
        )
        assert code == 0
        assert "Cannot write trace" in capsys.readouterr().err


class TestCustomResourceKeys:
    def test_resource_key_flag(self, capsys):
        nodes = [fx.make_node("gaudi-0", allocatable={"habana.ai/gaudi": "8"})]
        assert checker.one_shot(args_for(), nodes=nodes) == 2
        assert checker.one_shot(args_for("--resource-key", "habana.ai/gaudi"), nodes=nodes) == 0


class TestSlackIntegration:
    def _patch_send(self, monkeypatch, sent_log):
        def fake_send(url, message, **kwargs):
            sent_log.append({"url": url, "message": message, **kwargs})
            return True

        monkeypatch.setattr(notify, "send_slack_message", fake_send)

    def test_sent_when_webhook_given(self, monkeypatch, capsys):
        sent = []
        self._patch_send(monkeypatch, sent)
        code = checker.one_shot(
            args_for("--slack-webhook", "https://hooks.example/x"),
            nodes=fx.tpu_v5e_single_host(),
        )
        assert code == 0
        assert len(sent) == 1
        assert sent[0]["message"].startswith("✅")
        assert "Slack notification sent." in capsys.readouterr().out

    def test_only_on_error_suppresses_on_success(self, monkeypatch, capsys):
        sent = []
        self._patch_send(monkeypatch, sent)
        checker.one_shot(
            args_for("--slack-webhook", "https://x", "--slack-only-on-error"),
            nodes=fx.gpu_pool(1),
        )
        assert sent == []

    def test_only_on_error_fires_when_none_ready(self, monkeypatch, capsys):
        sent = []
        self._patch_send(monkeypatch, sent)
        code = checker.one_shot(
            args_for("--slack-webhook", "https://x", "--slack-only-on-error"),
            nodes=fx.gpu_pool(2, ready=False),
        )
        assert code == 3
        assert len(sent) == 1
        assert sent[0]["message"].startswith("⚠️")

    def test_json_mode_suppresses_console_confirmation(self, monkeypatch, capsys):
        # check-gpu-node.py:268-271.
        sent = []
        self._patch_send(monkeypatch, sent)
        checker.one_shot(
            args_for("--json", "--slack-webhook", "https://x"),
            nodes=fx.gpu_pool(1),
        )
        out = capsys.readouterr().out
        assert "Slack notification" not in out
        json.loads(out)  # still valid JSON payload

    def test_retry_settings_forwarded(self, monkeypatch):
        sent = []
        self._patch_send(monkeypatch, sent)
        checker.one_shot(
            args_for(
                "--slack-webhook", "https://x",
                "--slack-retry-count", "5",
                "--slack-retry-delay", "1.5",
                "--slack-username", "custom-bot",
            ),
            nodes=fx.gpu_pool(1),
        )
        assert sent[0]["max_retries"] == 5
        assert sent[0]["retry_delay"] == 1.5
        assert sent[0]["username"] == "custom-bot"

    def test_strict_slice_failure_alerts_with_degraded_header(self, monkeypatch, capsys):
        # exit 3 via --strict-slices must fire --slack-only-on-error and must
        # NOT be reported under a ✅ banner even though some hosts are Ready.
        sent = []
        self._patch_send(monkeypatch, sent)
        code = checker.one_shot(
            args_for(
                "--strict-slices", "--slack-webhook", "https://x", "--slack-only-on-error"
            ),
            nodes=fx.tpu_v5p_64_slice(not_ready=1),
        )
        assert code == 3
        assert len(sent) == 1
        assert sent[0]["message"].startswith("⚠️")
        assert "degraded" in sent[0]["message"]

    def test_slack_failure_not_fatal(self, monkeypatch, capsys):
        # check-gpu-node.py:269-271: delivery failure doesn't change exit code.
        monkeypatch.setattr(notify, "send_slack_message", lambda *a, **k: False)
        code = checker.one_shot(
            args_for("--slack-webhook", "https://x"), nodes=fx.gpu_pool(1)
        )
        assert code == 0
        assert "failed" in capsys.readouterr().err


class TestScale:
    """Large-cluster robustness: thousands of nodes through the full
    detect → group → report path, inside the <2 s north-star budget."""

    def test_big_cluster_counts_and_latency(self, capsys):
        import time

        nodes = fx.big_mixed_cluster(cpu=3000, gpu=1000, tpu_slices=16)
        args = args_for("--json")
        t0 = time.perf_counter()
        result = checker.run_check(args, nodes=nodes)
        elapsed_s = time.perf_counter() - t0
        assert result.exit_code == 0
        assert result.payload["total_nodes"] == 1000 + 16 * 64
        assert result.payload["total_chips"] == 1000 * 8 + 16 * 256
        assert len(result.payload["slices"]) == 16
        assert all(s["complete"] for s in result.payload["slices"])
        # 5024 nodes parsed, grouped, and reported: the in-process path must
        # stay well inside the 2 s budget (generous bound for slow CI).
        assert elapsed_s < 2.0, f"scale check took {elapsed_s:.2f}s"


class TestColdPathImports:
    def test_probe_less_check_keeps_heavy_modules_unloaded(self, tmp_path):
        # The cold-start budget's structural guard: a plain control-plane
        # check must not import jax, requests, PyYAML, or any probe
        # machinery (liveness subprocess plumbing, the report schema) — the
        # round-4/5 lazy-import work, pinned so a future top-of-function
        # import cannot silently re-tax every cron run.
        import subprocess
        import sys

        path = write_nodes(tmp_path, fx.tpu_v5e_256_slice())
        code = (
            "import sys\n"
            "from tpu_node_checker import checker, cli\n"
            f"args = cli.parse_args(['--json', '--nodes-json', {str(path)!r}])\n"
            "result = checker.run_check(args)\n"
            "assert result.exit_code == 0\n"
            "heavy = [m for m in ('jax', 'requests', 'yaml',\n"
            "                     'tpu_node_checker.probe.liveness',\n"
            "                     'tpu_node_checker.probe.schema',\n"
            "                     'tpu_node_checker.metrics')\n"
            "         if m in sys.modules]\n"
            "assert not heavy, f'cold path imported {heavy}'\n"
            "print('cold path lean')\n"
        )
        env = {k: v for k, v in __import__("os").environ.items()
               if k != "PALLAS_AXON_POOL_IPS"}
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True, env=env
        )
        assert proc.returncode == 0, proc.stderr
        assert "cold path lean" in proc.stdout
