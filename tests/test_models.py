"""Burn-in workload tests — single-device and sharded over the 8-device mesh."""

import jax
import numpy as np
import pytest

from tpu_node_checker.models import (
    BurninConfig,
    forward,
    init_params,
    make_train_step,
    param_specs,
    workload_probe,
)
from tpu_node_checker.parallel import MeshSpec, build_mesh

TINY = BurninConfig(vocab=64, d_model=32, n_heads=2, d_ff=64, n_layers=2, seq=16, batch=4)


class TestForward:
    def test_shapes(self):
        params = init_params(jax.random.PRNGKey(0), TINY)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, TINY.vocab)
        logits = forward(params, tokens, TINY)
        assert logits.shape == (4, 16, TINY.vocab)
        assert bool(jax.numpy.isfinite(logits).all())

    def test_causality(self):
        # Changing a future token must not change past logits.
        params = init_params(jax.random.PRNGKey(0), TINY)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 16), 0, TINY.vocab)
        logits_a = forward(params, tokens, TINY)
        tokens_b = tokens.at[0, -1].set((tokens[0, -1] + 1) % TINY.vocab)
        logits_b = forward(params, tokens_b, TINY)
        np.testing.assert_allclose(
            np.asarray(logits_a[0, :-1]), np.asarray(logits_b[0, :-1]), rtol=1e-5
        )

    def test_param_specs_mirror_params(self):
        params = init_params(jax.random.PRNGKey(0), TINY)
        specs = param_specs(TINY)
        assert jax.tree.structure(params) == jax.tree.structure(
            specs, is_leaf=lambda x: x is None or not isinstance(x, dict)
        )


class TestWorkloadProbe:
    def test_single_device_probe_healthy(self):
        r = workload_probe(TINY, steps=3)
        assert r.ok, r.error
        assert len(r.losses) == 3
        assert r.losses[-1] < r.losses[0]

    @pytest.mark.slow  # heavy XLA compile (13-21s); CI's slow step covers it
    def test_sharded_probe_over_mesh(self):
        mesh = build_mesh(MeshSpec((("data", 4), ("model", 2))))
        r = workload_probe(TINY, mesh=mesh, steps=3)
        assert r.ok, r.error
        assert r.losses[-1] < r.losses[0]

    @pytest.mark.slow  # heavy XLA compile (13-21s); CI's slow step covers it
    def test_sharded_matches_single_device(self):
        # GSPMD must not change the math: same seed, same loss trajectory.
        mesh = build_mesh(MeshSpec((("data", 2), ("model", 4))))
        r1 = workload_probe(TINY, steps=2, seed=7)
        r2 = workload_probe(TINY, mesh=mesh, steps=2, seed=7)
        assert r1.ok and r2.ok
        np.testing.assert_allclose(r1.losses, r2.losses, rtol=2e-2)

    def test_probe_never_raises(self):
        bad = BurninConfig(d_model=33, n_heads=2)  # indivisible heads
        r = workload_probe(bad, steps=1)
        assert not r.ok
        assert r.error

    @pytest.mark.slow  # heavy XLA compile (13-21s); CI's slow step covers it
    def test_flash_attention_matches_xla_attention(self):
        # Same seed, same data: the Pallas-forward/XLA-backward step must
        # track the pure-XLA step's loss trajectory.
        import dataclasses

        cfg = dataclasses.replace(TINY, seq=128)
        r_xla = workload_probe(cfg, steps=2, seed=5)
        r_flash = workload_probe(
            dataclasses.replace(cfg, attention="flash"), steps=2, seed=5
        )
        assert r_xla.ok and r_flash.ok, (r_xla.error, r_flash.error)
        np.testing.assert_allclose(r_xla.losses, r_flash.losses, rtol=1e-3)

    def test_flash_attention_rejects_mesh(self):
        import dataclasses

        cfg = dataclasses.replace(TINY, seq=128, attention="flash")
        mesh = build_mesh(MeshSpec((("data", 2), ("model", 4))))
        r = workload_probe(cfg, mesh=mesh, steps=1)
        assert not r.ok
        assert "single-device" in r.error

    def test_flash_attention_rejects_unaligned_seq(self):
        import dataclasses

        r = workload_probe(dataclasses.replace(TINY, attention="flash"), steps=1)
        assert not r.ok
        assert "seq % 128" in r.error

    @pytest.mark.slow  # heavy XLA compile (13-21s); CI's slow step covers it
    def test_remat_matches_no_remat(self):
        # jax.checkpoint trades FLOPs for HBM; the loss trajectory must be
        # bit-compatible up to float noise.
        import dataclasses

        r1 = workload_probe(TINY, steps=2, seed=3)
        r2 = workload_probe(
            dataclasses.replace(TINY, remat=True), steps=2, seed=3
        )
        assert r1.ok and r2.ok, (r1.error, r2.error)
        np.testing.assert_allclose(r1.losses, r2.losses, rtol=1e-4)


class TestCompilerContract:
    def test_sharded_step_emits_ici_collectives(self):
        # The design claim (DESIGN.md §4): GSPMD — not hand-rolled transports
        # — inserts the ICI collectives.  Pin it at the HLO level so a future
        # sharding-spec regression that silently de-parallelizes the step
        # (all specs replicated → zero collectives) fails loudly.
        import jax.numpy as jnp

        mesh = build_mesh(MeshSpec((("data", 2), ("model", 4))))
        step, init_fn = make_train_step(TINY, mesh)
        params, opt_state = init_fn(jax.random.PRNGKey(0))
        tokens = jnp.zeros((TINY.batch, TINY.seq), jnp.int32)
        hlo = step.lower(params, opt_state, tokens).compile().as_text()
        # Gradient sync over "data" + activation sums over "model":
        assert "all-reduce" in hlo
        # Tensor-parallel parameter/activation gathers:
        assert "all-gather" in hlo


class TestShardedStep:
    def test_params_actually_sharded(self):
        mesh = build_mesh(MeshSpec((("data", 2), ("model", 4))))
        step, init_fn = make_train_step(TINY, mesh)
        params, _ = init_fn(jax.random.PRNGKey(0))
        sh = params["layers"]["w1"].sharding
        assert sh.spec == jax.sharding.PartitionSpec(None, None, "model")
        # 8 devices each hold a shard of w1:
        assert len(params["layers"]["w1"].addressable_shards) == 8


class TestAdam:
    """The hand-rolled Adam (burnin._Adam) against a NumPy reference.

    Round-2 verdict #1 replaced ``optax.adam`` with ~40 in-package lines to
    keep the probe's dependency surface at requests+PyYAML+jax; that trade
    is only sound if the optimizer is pinned numerically.
    """

    def _numpy_adam(self, grads_seq, p0, lr=1e-3, b1=0.9, b2=0.999, eps=1e-8):
        p = np.array(p0, np.float32)
        mu = np.zeros_like(p)
        nu = np.zeros_like(p)
        for t, g in enumerate(grads_seq, start=1):
            g = np.asarray(g, np.float32)
            mu = b1 * mu + (1 - b1) * g
            nu = b2 * nu + (1 - b2) * g * g
            mu_hat = mu / (1 - b1**t)
            nu_hat = nu / (1 - b2**t)
            p = p - lr * mu_hat / (np.sqrt(nu_hat) + eps)
        return p

    def test_matches_reference_update(self):
        from tpu_node_checker.models.burnin import _Adam

        rng = np.random.default_rng(0)
        p0 = rng.normal(size=(5, 3)).astype(np.float32)
        grads_seq = [rng.normal(size=(5, 3)).astype(np.float32) for _ in range(7)]

        tx = _Adam(lr=1e-3)
        params = {"w": jax.numpy.asarray(p0)}
        state = tx.init(params)
        for g in grads_seq:
            updates, state = tx.update({"w": jax.numpy.asarray(g)}, state, params)
            params = _Adam.apply_updates(params, updates)

        expected = self._numpy_adam(grads_seq, p0)
        np.testing.assert_allclose(np.asarray(params["w"]), expected, rtol=1e-5, atol=1e-7)
        assert int(state["count"]) == len(grads_seq)

    def test_state_inherits_param_sharding(self):
        # Moments are zeros_like over sharded params → same layout, so the
        # sharded train step's opt-state shardings can be inferred (burnin
        # builds sharded_init exactly this way).
        from jax.sharding import NamedSharding, PartitionSpec as P

        from tpu_node_checker.models.burnin import _Adam

        mesh = build_mesh(MeshSpec((("data", 2), ("model", 4))))
        sh = NamedSharding(mesh, P(None, "model"))
        params = {"w": jax.device_put(jax.numpy.ones((4, 8)), sh)}
        state = _Adam().init(params)
        assert state["mu"]["w"].sharding == sh
        assert state["nu"]["w"].sharding == sh
