"""Cluster-access tests: kubeconfig parsing, precedence, in-cluster, client.

No network: the HTTP boundary is a fake ``requests.Session``-shaped object
(SURVEY §4 — "a CoreV1Api stub returning canned node lists" becomes a stub
session returning a canned NodeList).
"""

import base64
import json
import os
import stat
import sys

import pytest

from tests import fixtures as fx
from tpu_node_checker import cluster


def write_kubeconfig(path, server="https://1.2.3.4:6443", token="tok", extra_user=None):
    user = {"token": token}
    if extra_user:
        user = extra_user
    doc = {
        "apiVersion": "v1",
        "kind": "Config",
        "current-context": "ctx",
        "contexts": [{"name": "ctx", "context": {"cluster": "c", "user": "u"}}],
        "clusters": [{"name": "c", "cluster": {"server": server}}],
        "users": [{"name": "u", "user": user}],
    }
    import yaml

    path.write_text(yaml.safe_dump(doc))
    return str(path)


class TestKubeconfig:
    def test_token_auth(self, tmp_path):
        cfg = cluster.load_kubeconfig(write_kubeconfig(tmp_path / "kc"))
        assert cfg.server == "https://1.2.3.4:6443"
        assert cfg.token == "tok"
        assert cfg.verify is True

    def test_inline_ca_and_client_cert_data(self, tmp_path):
        ca = base64.b64encode(b"CADATA").decode()
        crt = base64.b64encode(b"CRT").decode()
        key = base64.b64encode(b"KEY").decode()
        doc = {
            "current-context": "ctx",
            "contexts": [{"name": "ctx", "context": {"cluster": "c", "user": "u"}}],
            "clusters": [
                {
                    "name": "c",
                    "cluster": {
                        "server": "https://s:6443/",
                        "certificate-authority-data": ca,
                    },
                }
            ],
            "users": [
                {
                    "name": "u",
                    "user": {"client-certificate-data": crt, "client-key-data": key},
                }
            ],
        }
        import yaml

        p = tmp_path / "kc"
        p.write_text(yaml.safe_dump(doc))
        cfg = cluster.load_kubeconfig(str(p))
        assert cfg.server == "https://s:6443"  # trailing slash stripped
        assert open(cfg.ca_file, "rb").read() == b"CADATA"
        cert, keyf = cfg.client_cert
        assert open(cert, "rb").read() == b"CRT"
        assert open(keyf, "rb").read() == b"KEY"
        # Credential material must not be world-readable.
        assert stat.S_IMODE(os.stat(keyf).st_mode) == 0o600

    def test_exec_plugin(self, tmp_path):
        plugin = tmp_path / "fake-auth"
        cred = {"apiVersion": "client.authentication.k8s.io/v1", "kind": "ExecCredential",
                "status": {"token": "exec-token"}}
        plugin.write_text(f"#!{sys.executable}\nprint('''{json.dumps(cred)}''')\n")
        plugin.chmod(0o755)
        cfg = cluster.load_kubeconfig(
            write_kubeconfig(
                tmp_path / "kc", extra_user={"exec": {"command": str(plugin)}}
            )
        )
        assert cfg.token == "exec-token"

    def test_exec_plugin_missing_command(self, tmp_path):
        with pytest.raises(cluster.ClusterConfigError, match="not found"):
            cluster.load_kubeconfig(
                write_kubeconfig(
                    tmp_path / "kc",
                    extra_user={"exec": {"command": "/nonexistent/definitely-not-here"}},
                )
            )

    def test_missing_context_rejected(self, tmp_path):
        p = tmp_path / "kc"
        p.write_text("apiVersion: v1\nkind: Config\n")
        with pytest.raises(cluster.ClusterConfigError, match="current-context"):
            cluster.load_kubeconfig(str(p))

    def test_explicit_context_override(self, tmp_path):
        import yaml

        doc = {
            "current-context": "a",
            "contexts": [
                {"name": "a", "context": {"cluster": "ca", "user": "u"}},
                {"name": "b", "context": {"cluster": "cb", "user": "u"}},
            ],
            "clusters": [
                {"name": "ca", "cluster": {"server": "https://a:1"}},
                {"name": "cb", "cluster": {"server": "https://b:1"}},
            ],
            "users": [{"name": "u", "user": {"token": "t"}}],
        }
        p = tmp_path / "kc"
        p.write_text(yaml.safe_dump(doc))
        assert cluster.load_kubeconfig(str(p), context="b").server == "https://b:1"


class TestPrecedence:
    """Discovery precedence mirrors check-gpu-node.py:160-169, plus in-cluster."""

    def test_flag_beats_env(self, tmp_path, monkeypatch):
        flag_kc = write_kubeconfig(tmp_path / "flag", server="https://flag:1")
        env_kc = write_kubeconfig(tmp_path / "env", server="https://env:1")
        monkeypatch.setenv("KUBECONFIG", env_kc)
        assert cluster.resolve_cluster_config(flag_kc).server == "https://flag:1"

    def test_env_used_when_exists(self, tmp_path, monkeypatch):
        env_kc = write_kubeconfig(tmp_path / "env", server="https://env:1")
        monkeypatch.setenv("KUBECONFIG", env_kc)
        assert cluster.resolve_cluster_config(None).server == "https://env:1"

    def test_env_path_list_first_existing_wins(self, tmp_path, monkeypatch):
        # kubectl semantics: $KUBECONFIG may be a pathsep-separated list.
        real = write_kubeconfig(tmp_path / "real", server="https://real:1")
        monkeypatch.setenv("KUBECONFIG", f"{tmp_path / 'missing'}{os.pathsep}{real}")
        assert cluster.resolve_cluster_config(None).server == "https://real:1"

    def test_credential_temp_files_registered_for_cleanup(self, tmp_path, monkeypatch):
        cleaned = []
        monkeypatch.setattr(cluster.atexit, "register", lambda fn, *a: cleaned.append(a))
        # Materialization is content-addressed (cache-key stability for the
        # keep-alive client cache); start clean so THIS load registers.
        monkeypatch.setattr(cluster, "_MATERIALIZED", {})
        key = base64.b64encode(b"KEY").decode()
        crt = base64.b64encode(b"CRT").decode()
        cfg = cluster.load_kubeconfig(
            write_kubeconfig(
                tmp_path / "kc",
                extra_user={"client-certificate-data": crt, "client-key-data": key},
            )
        )
        assert len(cleaned) == 2  # cert + key both registered for unlink
        assert {c[0] for c in cleaned} == set(cfg.client_cert)

    def test_env_ignored_when_missing(self, tmp_path, monkeypatch):
        # Reference behavior: $KUBECONFIG used only if the path exists (:165-167).
        monkeypatch.setenv("KUBECONFIG", str(tmp_path / "nope"))
        monkeypatch.setattr(cluster, "DEFAULT_KUBECONFIG", str(tmp_path / "default-nope"))
        monkeypatch.delenv("KUBERNETES_SERVICE_HOST", raising=False)
        with pytest.raises(cluster.ClusterConfigError):
            cluster.resolve_cluster_config(None)

    def test_in_cluster_fallback(self, tmp_path, monkeypatch):
        sa = tmp_path / "sa"
        sa.mkdir()
        (sa / "token").write_text("sa-token\n")
        (sa / "ca.crt").write_text("CA")
        monkeypatch.delenv("KUBECONFIG", raising=False)
        monkeypatch.setattr(cluster, "DEFAULT_KUBECONFIG", str(tmp_path / "nope"))
        monkeypatch.setattr(cluster, "SERVICE_ACCOUNT_DIR", str(sa))
        monkeypatch.setenv("KUBERNETES_SERVICE_HOST", "10.0.0.1")
        monkeypatch.setenv("KUBERNETES_SERVICE_PORT", "443")
        cfg = cluster.resolve_cluster_config(None)
        assert cfg.server == "https://10.0.0.1:443"
        assert cfg.token == "sa-token"
        assert cfg.source == "in-cluster"


class FakeSession:
    """requests.Session stand-in recording the single LIST call."""

    def __init__(self, items):
        self.items = items
        self.calls = []
        self.headers = {}
        self.verify = None
        self.cert = None
        self.auth = None

    def get(self, url, params=None, timeout=None):
        self.calls.append({"url": url, "params": params, "timeout": timeout})

        class R:
            status_code = 200

            def raise_for_status(self):
                pass

            def json(inner):
                return fx.node_list(self.items)

        return R()


class TestKubeClient:
    def test_list_nodes_single_call(self):
        cfg = cluster.ClusterConfig(server="https://api:6443", token="t")
        session = FakeSession(fx.tpu_v5e_single_host())
        nodes = cluster.KubeClient(cfg, session=session).list_nodes()
        assert len(nodes) == 1
        assert len(session.calls) == 1  # exactly one API call, as check-gpu-node.py:217
        assert session.calls[0]["url"] == "https://api:6443/api/v1/nodes"
        assert session.headers["Authorization"] == "Bearer t"

    def test_label_selector_param(self):
        cfg = cluster.ClusterConfig(server="https://api:6443")
        session = FakeSession([])
        cluster.KubeClient(cfg, session=session).list_nodes(
            label_selector="cloud.google.com/gke-tpu-accelerator"
        )
        assert session.calls[0]["params"] == {
            "labelSelector": "cloud.google.com/gke-tpu-accelerator",
            "limit": str(cluster.KubeClient.LIST_PAGE_LIMIT),
        }

    def test_pagination_disabled_drops_limit_param(self):
        cfg = cluster.ClusterConfig(server="https://api:6443")
        session = FakeSession([])
        cluster.KubeClient(cfg, session=session).list_nodes(page_limit=None)
        assert session.calls[0]["params"] == {}


class PagingFakeSession:
    """Session double serving a NodeList in pages via limit/continue."""

    def __init__(self, nodes, page_size, fail_410_at=None):
        self.nodes = nodes
        self.page_size = page_size
        self.fail_410_at = fail_410_at  # page index whose FIRST fetch 410s
        self.calls = []
        self.headers = {}
        self.verify = None
        self.cert = None
        self.auth = None

    def get(self, url, params=None, timeout=None):
        params = dict(params or {})
        self.calls.append({"url": url, "params": params})
        start = int(params.get("continue") or 0)
        outer = self

        class R:
            status_code = 200

            def raise_for_status(inner):
                if (
                    outer.fail_410_at is not None
                    and start == outer.fail_410_at
                ):
                    outer.fail_410_at = None  # expire once, then recover
                    raise cluster.ClusterAPIError(
                        "HTTP 410 from /nodes: continue token expired",
                        status_code=410,
                    )

            def json(inner):
                page = outer.nodes[start:start + outer.page_size]
                doc = fx.node_list(page)
                if start + outer.page_size < len(outer.nodes):
                    doc["metadata"] = {"continue": str(start + outer.page_size)}
                return doc

        return R()


class TestPaginatedList:
    def test_three_pages_all_nodes_seen(self):
        nodes = fx.tpu_v5e_256_slice()  # 64 node objects
        cfg = cluster.ClusterConfig(server="https://api:6443")
        session = PagingFakeSession(nodes, page_size=30)
        got = cluster.KubeClient(cfg, session=session).list_nodes(page_limit=30)
        assert len(got) == 64
        assert [n["metadata"]["name"] for n in got] == [
            n["metadata"]["name"] for n in nodes
        ]
        assert len(session.calls) == 3
        # Every page carries the limit; followers carry the continue token.
        assert all(c["params"]["limit"] == "30" for c in session.calls)
        assert "continue" not in session.calls[0]["params"]
        assert session.calls[1]["params"]["continue"] == "30"
        assert session.calls[2]["params"]["continue"] == "60"

    def test_expired_continue_token_restarts_once(self):
        nodes = fx.tpu_v5e_256_slice()
        cfg = cluster.ClusterConfig(server="https://api:6443")
        session = PagingFakeSession(nodes, page_size=40, fail_410_at=40)
        got = cluster.KubeClient(cfg, session=session).list_nodes(page_limit=40)
        # Page 2's first fetch 410s (snapshot compacted); the LIST restarts
        # from scratch and completes — no duplicates, no losses.
        assert len(got) == 64
        assert len({n["metadata"]["name"] for n in got}) == 64
        assert len(session.calls) == 4  # p1, 410, p1 again, p2

    def test_410_on_first_page_is_fatal_not_a_loop(self):
        # A 410 with NO continue token outstanding is a real error (e.g.
        # proxy nonsense), not an expired snapshot — never retry-loop it.
        nodes = fx.tpu_v5e_single_host()
        cfg = cluster.ClusterConfig(server="https://api:6443")
        session = PagingFakeSession(nodes, page_size=40, fail_410_at=0)
        with pytest.raises(cluster.ClusterAPIError):
            cluster.KubeClient(cfg, session=session).list_nodes(page_limit=40)
        assert len(session.calls) == 1

    def test_three_pages_over_real_http_transport(self):
        # End-to-end over the stdlib transport against a fake API server:
        # limit/continue round-trip through real URL encoding and JSON.
        nodes = fx.tpu_v5e_256_slice()
        seen: list = []
        server = fx.serve_http(fx.paged_nodelist_handler(nodes, seen))
        try:
            cfg = cluster.ClusterConfig(
                server=f"http://127.0.0.1:{server.server_address[1]}"
            )
            got = cluster.KubeClient(cfg).list_nodes(page_limit=22)
            assert len(got) == 64
            assert len({n["metadata"]["name"] for n in got}) == 64
            # The limit param must actually cross the wire: the shared
            # handler defaults a MISSING limit to one all-nodes page, so
            # pin the 3-page walk (ceil(64/22)) explicitly.
            assert seen == [0, 22, 44]
        finally:
            server.shutdown()


class ContentPagingSession:
    """Paging double whose responses carry raw bytes — what engages the
    fetch/decode pipeline (a body-less double never prefetches)."""

    def __init__(self, nodes, page_size, fail_410_at=None, fake_last_token=None):
        self.nodes = nodes
        self.page_size = page_size
        self.fail_410_at = fail_410_at  # start offset whose FIRST fetch 410s
        self.fake_last_token = fake_last_token  # plant '"continue":' bait
        self.calls = []
        self.headers = {}
        self.verify = None
        self.cert = None
        self.auth = None

    def get(self, url, params=None, timeout=None):
        params = dict(params or {})
        self.calls.append(params)
        try:
            start = int(params.get("continue") or 0)
        except ValueError:
            start = len(self.nodes)  # a mispeeked token: serve an empty tail
        if self.fail_410_at is not None and start == self.fail_410_at:
            self.fail_410_at = None  # expire once, then recover
            raise cluster.ClusterAPIError(
                "HTTP 410 from /nodes: continue token expired", status_code=410
            )
        page = list(self.nodes[start:start + self.page_size])
        last = start + self.page_size >= len(self.nodes)
        if last and self.fake_last_token is not None:
            # An item whose own key is literally "continue" — byte-level
            # bait for peek_continue on a page whose metadata has none.
            page.append({"metadata": {"name": "bait"},
                         "continue": self.fake_last_token})
        doc = {"kind": "NodeList", "items": page}
        if not last:
            doc["metadata"] = {"continue": str(start + self.page_size)}
        body = json.dumps(doc).encode()

        class R:
            status_code = 200
            content = body

            def raise_for_status(inner):
                pass

            def json(inner):
                return json.loads(body)

        return R()


class TestPipelinedWalk:
    """cluster._paged_list's single-slot fetch/decode pipeline: page N+1 is
    in flight while page N decodes, with the serial walk's exact request
    set, restart semantics, and result.  The pipeline is decode-cost
    adaptive (tier-0 page reuse decodes too fast to be worth a worker
    handoff), so these tests pin it ON through the test seam."""

    @pytest.fixture(autouse=True)
    def _always_pipeline(self, monkeypatch):
        monkeypatch.setattr(cluster, "_PREFETCH_MIN_DECODE_S", 0.0)

    def _client(self, session):
        cfg = cluster.ClusterConfig(server="https://api:6443")
        return cluster.KubeClient(cfg, session=session)

    def test_pipelined_walk_sends_exactly_one_request_per_page(self):
        nodes = fx.tpu_v5e_256_slice()
        session = ContentPagingSession(nodes, page_size=20)
        got = self._client(session).list_nodes(page_limit=20)
        assert [n["metadata"]["name"] for n in got] == [
            n["metadata"]["name"] for n in nodes
        ]
        # ceil(64/20) = 4 pages, no speculative extras: every prefetch was
        # for a token the decode then confirmed.
        assert len(session.calls) == 4
        assert [c.get("continue") for c in session.calls] == [
            None, "20", "40", "60"
        ]

    def test_mispeeked_token_wastes_at_most_one_request_never_the_result(self):
        nodes = fx.tpu_v5e_single_host()
        session = ContentPagingSession(nodes, page_size=10,
                                       fake_last_token="999")
        got = self._client(session).list_nodes(page_limit=10)
        # The bait item is a real (garbage) item of the last page; the walk
        # terminates on the authoritative metadata (no continue) and the
        # speculative fetch — if it won the race — is discarded unread.
        assert [n["metadata"].get("name") for n in got] == [
            n["metadata"]["name"] for n in nodes
        ] + ["bait"]
        real = [c for c in session.calls if c.get("continue") != "999"]
        assert len(real) == 1
        assert len(session.calls) <= 2

    def test_410_in_prefetched_page_restarts_once_cleanly(self):
        nodes = fx.tpu_v5e_256_slice()
        session = ContentPagingSession(nodes, page_size=40, fail_410_at=40)
        got = self._client(session).list_nodes(page_limit=40)
        assert len(got) == 64
        assert len({n["metadata"]["name"] for n in got}) == 64
        # p1, p2-prefetch (410s on the worker, re-raised on the caller),
        # then the clean restart: p1 again, p2.
        assert len(session.calls) == 4

    def test_projected_walk_same_fleet_as_raw_walk(self):
        nodes = fx.tpu_v5e_256_slice(not_ready=2)  # 64 hosts → 3 pages of 30
        raw = self._client(
            ContentPagingSession(nodes, page_size=30)
        ).list_nodes(page_limit=30)
        client = self._client(ContentPagingSession(nodes, page_size=30))
        fleet = client.list_nodes_projected(page_limit=30)
        from tpu_node_checker import fastpath

        assert fleet.docs() == [fastpath.project_node_doc(n) for n in raw]
        assert [p.name for p in fleet] == [
            n["metadata"]["name"] for n in raw
        ]
        # The projector lives on the client: a second identical walk is
        # pure page reuse.
        before = dict(client.projector_stats)
        fleet2 = client.list_nodes_projected(page_limit=30)
        stats = client.projector_stats
        assert stats["pages_unchanged"] - before["pages_unchanged"] == 3
        assert stats["items_decoded"] == before["items_decoded"]
        assert [a is b for a, b in zip(fleet, fleet2)] == [True] * len(fleet)


class TestListTruncation:
    """No-silent-caps: a page-budget-exhausted walk is counted and returned
    as an explicit verdict, never silently dropped."""

    class EndlessEventsSession:
        """Always hands back another continue token: the walk can only end
        on its page budget."""

        headers: dict = {}
        verify = cert = auth = None

        def __init__(self):
            self.calls = 0

        def get(self, url, params=None, timeout=None):
            self.calls += 1
            token = int(dict(params or {}).get("continue") or 0) + 1
            body = json.dumps({
                "items": [{"type": "Warning", "reason": f"R{token}",
                           "message": "m"}],
                "metadata": {"continue": str(token)},
            }).encode()

            class R:
                status_code = 200
                content = body

                def raise_for_status(inner):
                    pass

                def json(inner):
                    return json.loads(body)

            return R()

    def test_events_walk_truncation_is_counted_and_reported(self, capsys):
        cfg = cluster.ClusterConfig(server="https://api:6443")
        session = self.EndlessEventsSession()
        client = cluster.KubeClient(cfg, session=session)
        items, truncated = client.list_node_events_paged("node-1")
        assert truncated is True
        assert len(items) == cluster.KubeClient.EVENTS_MAX_PAGES
        assert "newest events may be missing" in capsys.readouterr().err
        assert client.transport_stats()["list_truncated"] == {"events": 1}
        # The legacy single-value accessor still walks and warns, and the
        # counter keeps counting.
        client.list_node_events("node-2")
        assert client.transport_stats()["list_truncated"] == {"events": 2}

    def test_healthy_walks_leave_no_truncation_key(self):
        nodes = fx.tpu_v5e_single_host()
        cfg = cluster.ClusterConfig(server="https://api:6443")
        client = cluster.KubeClient(
            cfg, session=ContentPagingSession(nodes, page_size=10)
        )
        client.list_nodes(page_limit=10)
        # Healthy payloads must stay byte-identical to the pre-truncation
        # surface: the key is absent, not zero.
        assert "list_truncated" not in client.transport_stats()


class TestStdlibSession:
    """The default stdlib transport (requests is off the happy path)."""

    @pytest.fixture
    def http_server(self):
        from http.server import BaseHTTPRequestHandler

        seen = []

        class Handler(BaseHTTPRequestHandler):
            def _respond(self, status, body=b'{"items": []}'):
                seen.append(
                    {
                        "method": self.command,
                        "path": self.path,
                        "auth": self.headers.get("Authorization"),
                        "content_type": self.headers.get("Content-Type"),
                    }
                )
                self.send_response(status)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if "redirect" in self.path:
                    # Record BEFORE responding: the client-side assertion can
                    # run the moment the response bytes land.
                    seen.append({"method": self.command, "path": self.path})
                    self.send_response(302)
                    self.send_header("Location", "http://127.0.0.1:1/elsewhere")
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                self._respond(404 if "missing" in self.path else 200)

            def do_PATCH(self):
                self._respond(200)

            def log_message(self, *args):
                pass

        server = fx.serve_http(Handler)
        yield f"http://127.0.0.1:{server.server_address[1]}", seen
        server.shutdown()

    def test_get_encodes_params_and_parses_json(self, http_server):
        base, seen = http_server
        s = cluster._StdlibSession()
        resp = s.get(f"{base}/api/v1/nodes", params={"labelSelector": "a=b,c"}, timeout=5)
        resp.raise_for_status()
        assert resp.json() == {"items": []}
        assert seen[0]["path"] == "/api/v1/nodes?labelSelector=a%3Db%2Cc"

    def test_basic_auth_header(self, http_server):
        base, seen = http_server
        s = cluster._StdlibSession()
        s.auth = ("user", "pass")
        s.get(f"{base}/x", timeout=5).raise_for_status()
        import base64

        assert seen[0]["auth"] == "Basic " + base64.b64encode(b"user:pass").decode()

    def test_bearer_header_via_headers_dict(self, http_server):
        base, seen = http_server
        s = cluster._StdlibSession()
        s.headers["Authorization"] = "Bearer tok"
        s.get(f"{base}/x", timeout=5)
        assert seen[0]["auth"] == "Bearer tok"

    def test_non_2xx_raises_on_raise_for_status_not_on_request(self, http_server):
        base, _ = http_server
        s = cluster._StdlibSession()
        resp = s.get(f"{base}/missing", timeout=5)  # must NOT raise here
        assert resp.status_code == 404
        with pytest.raises(cluster.ClusterAPIError, match="HTTP 404"):
            resp.raise_for_status()

    def test_patch_preserves_content_type(self, http_server):
        base, seen = http_server
        s = cluster._StdlibSession()
        s.patch(
            f"{base}/api/v1/nodes/n",
            data='{"spec": {"unschedulable": true}}',
            headers={"Content-Type": "application/strategic-merge-patch+json"},
            timeout=5,
        ).raise_for_status()
        assert seen[0]["method"] == "PATCH"
        assert seen[0]["content_type"] == "application/strategic-merge-patch+json"

    def test_redirects_refused_and_auth_not_resent(self, http_server):
        # A 302 must surface as an error, never be followed: urllib's default
        # redirect handler re-sends Authorization to the redirect target —
        # a cluster-token leak if the API endpoint is MITM'd or misconfigured.
        base, seen = http_server
        s = cluster._StdlibSession()
        s.headers["Authorization"] = "Bearer secret"
        resp = s.get(f"{base}/redirect", timeout=5)
        assert resp.status_code == 302
        with pytest.raises(cluster.ClusterAPIError, match="HTTP 302"):
            resp.raise_for_status()
        # Exactly one request reached the server — nothing was re-sent.
        assert len(seen) == 1

    def test_tls_context_cached_and_http_never_builds_one(self, http_server):
        s = cluster._StdlibSession()
        assert s._context() is s._context()  # built once, cached
        # A plain-http request must not build an SSL context at all (the
        # system CA load costs ~20 ms — a per-check tax http endpoints must
        # not pay).  Pinned against the NEW pooled transport.
        base, _ = http_server
        s2 = cluster._StdlibSession()
        calls = []
        orig = s2._context
        s2._context = lambda: calls.append(1) or orig()
        s2.get(f"{base}/x", timeout=5).raise_for_status()
        assert calls == []
        assert s2._ssl_ctx is None

    def test_uppercase_scheme_is_case_insensitive(self, http_server):
        # RFC 3986: the scheme is case-insensitive.  "HTTP://…" must work
        # against a plain server (and "HTTPS://…" would select the TLS
        # connection class, same as lowercase).
        base, seen = http_server
        s = cluster._StdlibSession()
        resp = s.get(base.replace("http://", "HTTP://") + "/x", timeout=5)
        resp.raise_for_status()
        assert seen[0]["path"] == "/x"

    def test_unsupported_scheme_rejected(self):
        s = cluster._StdlibSession()
        with pytest.raises(cluster.ClusterAPIError, match="scheme"):
            s.get("ftp://127.0.0.1/x", timeout=5)

    def test_kube_client_defaults_to_stdlib_session(self):
        cfg = cluster.ClusterConfig(server="https://api:6443", token="t")
        client = cluster.KubeClient(cfg)
        assert isinstance(client._session, cluster._StdlibSession)
        assert client._session.headers["Authorization"] == "Bearer t"
