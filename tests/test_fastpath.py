"""Relist fast path: projection decoding, fetch/decode pipelining, and
content-addressed node reuse (DESIGN §16).

The contract under test, in one line: every decode strategy — byte-level
projection, affix reuse, oracle fallback — must produce the SAME projected
fleet the ``json.loads`` oracle would, and reuse must be provably
by-reference (object identity, extraction counters), never semantic
guesswork.  Fuzzing is stdlib-only (seeded ``random``): tier-1 must run
without hypothesis.
"""

import json
import random

import pytest

from tests import fixtures as fx
from tpu_node_checker import fastpath
from tpu_node_checker.detect import extract_node_info
from tpu_node_checker.fastpath.projection import _decode_page_text
from tpu_node_checker.report import _node_entry


class _Resp:
    """requests-shaped response double carrying raw bytes."""

    def __init__(self, body, status=200):
        self.content = body if isinstance(body, bytes) else body.encode()
        self.status_code = status

    def raise_for_status(self):
        pass

    def json(self):
        return json.loads(self.content)


def _page_body(items, meta=None) -> bytes:
    doc = {"kind": "NodeList", "apiVersion": "v1", "items": items}
    if meta:
        doc["metadata"] = meta
    return json.dumps(doc).encode()


def _noisy_node(i: int, ready: bool = True) -> dict:
    """A node with the wire noise the projection exists to skip."""
    node = fx.make_node(
        f"gke-tpu-fast-{i:03d}", ready=ready,
        allocatable={"google.com/tpu": "4"},
    )
    node["metadata"]["managedFields"] = [
        {"manager": "kubelet", "operation": "Update",
         "fieldsV1": {"f:status": {f"f:field{j}": {}} for j in range(20)}}
    ]
    node["status"]["images"] = [
        {"names": [f"gcr.io/proj/img{j}@sha256:{'ab' * 16}"], "sizeBytes": 1 << 30}
        for j in range(10)
    ]
    node["status"]["conditions"].append(
        {"type": "DiskPressure", "status": "False",
         "lastHeartbeatTime": f"2026-08-03T10:{i % 60:02d}:00Z",
         "lastTransitionTime": "2026-08-01T00:00:00Z"}
    )
    return node


class TestProjectionGrammar:
    def test_noise_dropped_grading_fields_kept(self):
        node = _noisy_node(0)
        doc = fastpath.project_node_doc(node)
        assert set(doc) == {"metadata", "spec", "status"}
        assert "managedFields" not in doc["metadata"]
        assert "images" not in doc["status"]
        assert doc["metadata"]["name"] == node["metadata"]["name"]
        # Kept values are shared by reference, not copied.
        assert doc["metadata"]["labels"] is node["metadata"]["labels"]
        assert doc["status"]["allocatable"] is node["status"]["allocatable"]

    def test_condition_heartbeats_excluded(self):
        node = _noisy_node(1)
        doc = fastpath.project_node_doc(node)
        for cond in doc["status"]["conditions"]:
            assert "lastHeartbeatTime" not in cond
            assert "lastTransitionTime" not in cond
        # A heartbeat-only change must hash identically (the O(changes)
        # property at relist).
        before = fastpath.grading_digest(fastpath.project_node_doc(node))
        for cond in node["status"]["conditions"]:
            if "lastHeartbeatTime" in cond:
                cond["lastHeartbeatTime"] = "2026-08-03T23:59:59Z"
        after = fastpath.grading_digest(fastpath.project_node_doc(node))
        assert before == after

    def test_grading_change_changes_digest(self):
        node = _noisy_node(2)
        before = fastpath.grading_digest(fastpath.project_node_doc(node))
        for cond in node["status"]["conditions"]:
            if cond.get("type") == "Ready":
                cond["status"] = "False"
        after = fastpath.grading_digest(fastpath.project_node_doc(node))
        assert before != after

    def test_extract_parity_across_fixture_fleets(self):
        # The acceptance contract: a node graded through its projection is
        # byte-identical (entry-wise) to the same node graded whole.
        fleets = [
            fx.tpu_v5e_256_slice(),
            fx.tpu_v5p_64_slice(not_ready=3),
            fx.big_mixed_cluster(),
            [_noisy_node(i, ready=i % 3 > 0) for i in range(8)],
        ]
        for fleet in fleets:
            for node in fleet:
                full = extract_node_info(node)
                projected = extract_node_info(fastpath.project_node_doc(node))
                assert _node_entry(full) == _node_entry(projected), (
                    node.get("metadata", {}).get("name")
                )

    def test_garbage_shapes_tolerated(self):
        for garbage in (None, [], "x", 7, {"metadata": "nope"},
                        {"spec": None, "status": []}):
            doc = fastpath.project_node_doc(garbage)
            assert isinstance(doc, dict)
            # And grades like the raw shape does.
            assert _node_entry(extract_node_info(garbage)) == _node_entry(
                extract_node_info(doc)
            )


# --------------------------------------------------------------------------- #
# The scanner vs the json.loads oracle
# --------------------------------------------------------------------------- #


def _fuzz_string(rng: random.Random) -> str:
    """Strings built to confuse a byte-level walker: escaped quotes,
    backslashes, unicode escapes, braces/brackets/commas INSIDE strings."""
    pieces = []
    for _ in range(rng.randrange(0, 12)):
        pieces.append(rng.choice([
            '"', "\\", "{", "}", "[", "]", ",", ":", "x" * rng.randrange(1, 40),
            "é", "☃", "\n", "\t", '"continue":', "}{][",
            "\\u0041", "末端", " ",
        ]))
    return "".join(pieces)


def _fuzz_value(rng: random.Random, depth: int = 0):
    kinds = ["str", "int", "float", "bool", "null"]
    if depth < 3:
        kinds += ["obj", "arr"]
    kind = rng.choice(kinds)
    if kind == "str":
        return _fuzz_string(rng)
    if kind == "int":
        return rng.randrange(-(10 ** 9), 10 ** 9)
    if kind == "float":
        return rng.randrange(-(10 ** 6), 10 ** 6) / 7.0
    if kind == "bool":
        return rng.random() < 0.5
    if kind == "null":
        return None
    if kind == "obj":
        return {
            _fuzz_string(rng) or "k": _fuzz_value(rng, depth + 1)
            for _ in range(rng.randrange(0, 5))
        }
    return [_fuzz_value(rng, depth + 1) for _ in range(rng.randrange(0, 5))]


def _fuzz_page_text(rng: random.Random) -> str:
    """One LIST-page JSON document: items (sometimes huge, sometimes null),
    metadata, extra top-level keys, duplicate keys, odd whitespace."""
    items = [_fuzz_value(rng, 1) for _ in range(rng.randrange(0, 6))]
    if rng.random() < 0.3:
        # A huge skipped run: managedFields-sized noise inside one item.
        items.append({"metadata": {"name": "big"},
                      "noise": ["pad" * 50] * rng.randrange(50, 200)})
    parts = ['"kind": "NodeList"']
    if rng.random() < 0.15:
        parts.append('"items": null')
    else:
        parts.append(f'"items": {json.dumps(items, ensure_ascii=False)}')
    if rng.random() < 0.8:
        meta = {"resourceVersion": str(rng.randrange(10 ** 6))}
        if rng.random() < 0.5:
            meta["continue"] = f"tok{rng.randrange(100)}"
        parts.append(f'"metadata": {json.dumps(meta)}')
    if rng.random() < 0.3:
        parts.append(f'"extra": {json.dumps(_fuzz_value(rng, 1), ensure_ascii=False)}')
    if rng.random() < 0.2:
        # Duplicate top-level key: JSON semantics are last-wins, both ways.
        parts.append(f'"items": {json.dumps([_fuzz_value(rng, 2)], ensure_ascii=False)}')
    rng.shuffle(parts)
    ws = rng.choice(["", " ", "\n", "\t \n"])
    return "{" + ws + ("," + ws).join(parts) + ws + "}"


class TestScannerOracleEquivalence:
    def test_fuzz_pages_match_json_loads(self):
        rng = random.Random(0xFA57)
        for case in range(300):
            text = _fuzz_page_text(rng)
            doc = json.loads(text)
            items, spans, meta = _decode_page_text(text)
            want_items = doc.get("items") or []
            if not isinstance(want_items, list):
                want_items = []
            want_meta = doc.get("metadata") or {}
            assert items == want_items, (case, text[:200])
            assert meta == (want_meta if isinstance(want_meta, dict) else {}), case
            assert len(spans) == len(items)

    def test_fuzz_projector_end_to_end_matches_oracle(self):
        rng = random.Random(0xBEEF)
        projector = fastpath.ListProjector()
        for case in range(100):
            text = _fuzz_page_text(rng)
            body = text.encode()
            nodes, meta = projector.decode_page(_Resp(body), 0)
            oracle_items, oracle_meta = fastpath.oracle_decode_page(_Resp(body))
            assert [p.doc for p in nodes] == [
                fastpath.project_node_doc(it) for it in oracle_items
            ], case
            assert meta == oracle_meta, case

    def test_malformed_pages_fall_back_to_oracle_errors(self):
        projector = fastpath.ListProjector()
        # Truly broken bodies: the scanner must not "succeed" differently
        # from the oracle — both paths surface a decode error.
        for bad in (b"[1, 2", b'{"items": [}', b"", b'{"items": [1,]}'):
            with pytest.raises(ValueError):
                projector.decode_page(_Resp(bad), 0)
        # Non-UTF-8: the oracle tolerates latin-1-ish bytes via loads(bytes)
        # only when they are valid JSON encodings; a broken encoding errors.
        with pytest.raises(ValueError):
            projector.decode_page(_Resp(b'{"items": ["\xff\xfe"]}'), 0)

    def test_non_object_page_shapes(self):
        projector = fastpath.ListProjector()
        # A top-level array (not a k8s LIST shape): the oracle returns it
        # as the item list; the scanner falls back and must agree.
        nodes, meta = projector.decode_page(_Resp(b"[{}, {}]"), 0)
        assert [p.doc for p in nodes] == [{}, {}]
        assert meta == {}
        assert projector.stats["pages_fallback"] >= 1


class TestPeekContinue:
    def test_token_found(self):
        body = _page_body([{"a": 1}], meta={"continue": "500", "resourceVersion": "9"})
        assert fastpath.peek_continue(body) == "500"

    def test_absent_token_is_none(self):
        assert fastpath.peek_continue(_page_body([{"a": 1}])) is None
        assert fastpath.peek_continue(None) is None
        assert fastpath.peek_continue(b"") is None

    def test_escaped_or_non_ascii_tokens_refused(self):
        # Escapes inside the token cannot be resolved bytewise: no peek.
        assert fastpath.peek_continue(b'{"metadata": {"continue": "a\\"b"}}') is None
        assert fastpath.peek_continue(
            '{"metadata": {"continue": "toké"}}'.encode()
        ) is None
        assert fastpath.peek_continue(b'{"metadata": {"continue": ""}}') is None
        assert fastpath.peek_continue(b'{"metadata": {"continue": 7}}') is None

    def test_rfind_takes_the_last_occurrence(self):
        # An annotation mentioning "continue" earlier in the body must not
        # shadow the real metadata token at the end.
        body = (b'{"items": [{"metadata": {"annotations": '
                b'{"note": "\\"continue\\": \\"FAKE\\""}}}], '
                b'"metadata": {"continue": "real"}}')
        assert fastpath.peek_continue(body) == "real"


# --------------------------------------------------------------------------- #
# Reuse tiers: whole-page equality, affix byte-runs
# --------------------------------------------------------------------------- #


class TestListProjectorReuse:
    def _decode(self, projector, items, meta=None, index=0):
        return projector.decode_page(_Resp(_page_body(items, meta)), index)

    def test_tier0_identical_body_reuses_everything(self):
        items = [_noisy_node(i) for i in range(10)]
        projector = fastpath.ListProjector()
        nodes1, _ = self._decode(projector, items)
        nodes2, _ = self._decode(projector, items)
        assert projector.stats["pages_unchanged"] == 1
        assert nodes1 is nodes2  # the page's node list itself, by reference

    def test_affix_reuse_one_changed_node_mid_page(self):
        items = [_noisy_node(i) for i in range(20)]
        projector = fastpath.ListProjector()
        nodes1, _ = self._decode(projector, items)
        for cond in items[10]["status"]["conditions"]:
            if cond.get("type") == "Ready":
                cond["status"] = "False"
        nodes2, _ = self._decode(projector, items)
        assert projector.stats["items_reused"] == 19
        assert projector.stats["items_decoded"] == 20 + 1
        # Reused nodes are the SAME ProjectedNode objects.
        for i, (a, b) in enumerate(zip(nodes1, nodes2)):
            if i == 10:
                assert a is not b and a.digest != b.digest
            else:
                assert a is b
        # And the projected fleet still equals the oracle's view.
        assert [p.doc for p in nodes2] == [
            fastpath.project_node_doc(it) for it in items
        ]

    def test_affix_reuse_survives_insert_and_delete(self):
        items = [_noisy_node(i) for i in range(12)]
        projector = fastpath.ListProjector()
        self._decode(projector, items)
        # Insert near the front: the suffix run shifts but still maps.
        grown = items[:2] + [_noisy_node(99)] + items[2:]
        nodes, _ = self._decode(projector, grown)
        assert [p.doc for p in nodes] == [
            fastpath.project_node_doc(it) for it in grown
        ]
        assert projector.stats["items_reused"] > 0
        # Delete from the middle: prefix + shifted suffix again.
        shrunk = grown[:5] + grown[7:]
        nodes, _ = self._decode(projector, shrunk)
        assert [p.doc for p in nodes] == [
            fastpath.project_node_doc(it) for it in shrunk
        ]

    def test_fallback_page_recovers_to_fast_path(self):
        items = [_noisy_node(i) for i in range(4)]
        projector = fastpath.ListProjector()
        with pytest.raises(ValueError):
            projector.decode_page(_Resp(b'{"items": ['), 0)
        # A clean walk after the error decodes normally...
        nodes1, _ = self._decode(projector, items)
        decoded_before = projector.stats["pages_decoded"]
        # ...and the next identical walk rides tier-0 again.
        nodes2, _ = self._decode(projector, items)
        assert nodes1 is nodes2
        assert projector.stats["pages_decoded"] == decoded_before

    def test_kill_switch_forces_oracle(self, monkeypatch):
        monkeypatch.setenv("TNC_PROJECTION", "off")
        items = [_noisy_node(i) for i in range(3)]
        projector = fastpath.ListProjector()
        nodes, meta = self._decode(projector, items)
        assert projector.stats["pages_fallback"] == 1
        assert projector.stats["pages_decoded"] == 0
        # The fallback produces the same ProjectedNode contract.
        assert [p.doc for p in nodes] == [
            fastpath.project_node_doc(it) for it in items
        ]

    def test_doubles_without_content_use_oracle(self):
        class _NoContent:
            def json(self):
                return {"items": [{"metadata": {"name": "n1"}}], "metadata": {}}

        projector = fastpath.ListProjector()
        nodes, _ = projector.decode_page(_NoContent(), 0)
        assert nodes[0].name == "n1"
        assert projector.stats["pages_fallback"] == 1


class TestNodeReuseCache:
    def _fleet(self, items):
        projector = fastpath.ListProjector()
        nodes, _ = projector.decode_page(_Resp(_page_body(items)), 0)
        return fastpath.ProjectedFleet(nodes, "1", projector.reuse)

    def test_unchanged_digest_reuses_info_and_entry_by_reference(self):
        items = [_noisy_node(i) for i in range(6)]
        fleet = self._fleet(items)
        accel1, ready1, entries1, changed1 = fleet.reuse.select(fleet, None)
        assert changed1 == frozenset(p.name for p in fleet)
        assert fleet.reuse.extracts == 6
        accel2, ready2, entries2, changed2 = fleet.reuse.select(fleet, None)
        assert changed2 == frozenset()
        assert fleet.reuse.extracts == 6  # zero re-extraction
        for a, b in zip(accel1, accel2):
            assert a is b
        for a, b in zip(entries1, entries2):
            assert a is b

    def test_changed_and_removed_names_reported(self):
        # One projector across walks — the shape list_nodes_projected
        # drives: the SAME reuse cache sees both fleets.
        items = [_noisy_node(i) for i in range(6)]
        projector = fastpath.ListProjector()
        nodes, _ = projector.decode_page(_Resp(_page_body(items)), 0)
        fleet = fastpath.ProjectedFleet(nodes, "1", projector.reuse)
        fleet.reuse.select(fleet, None)
        extracts = fleet.reuse.extracts
        for cond in items[2]["status"]["conditions"]:
            if cond.get("type") == "Ready":
                cond["status"] = "False"
        smaller = items[:5]  # node 5 removed
        nodes2, _ = projector.decode_page(_Resp(_page_body(smaller)), 0)
        fleet2 = fastpath.ProjectedFleet(nodes2, "2", projector.reuse)
        accel, ready, entries, changed = fleet2.reuse.select(fleet2, None)
        assert changed == {items[2]["metadata"]["name"],
                           items[5]["metadata"]["name"]}
        assert fleet2.reuse.extracts == extracts + 1  # only the flipped node
        assert len(accel) == 5
        assert sum(1 for n in accel if n.ready) == 4

    def test_registry_change_invalidates_everything(self):
        from tpu_node_checker.resources import default_registry

        items = [_noisy_node(i) for i in range(3)]
        fleet = self._fleet(items)
        reg = default_registry()
        fleet.reuse.select(fleet, reg)
        assert fleet.reuse.extracts == 3
        fleet.reuse.select(fleet, reg.with_extra_keys(["corp.example/npu"]))
        assert fleet.reuse.extracts == 6  # full re-extract under the new key


class TestReuseAllowed:
    def test_plain_args_allow_gated_flags_refuse(self):
        from tpu_node_checker import cli

        assert fastpath.reuse_allowed(cli.parse_args(["--json"]))
        for flag in (["--probe"], ["--node-events"],
                     ["--probe-results", "/tmp/x"],
                     ["--history", "/tmp/h.jsonl"],
                     ["--cordon-failed", "--probe"]):
            args = cli.parse_args(flag + ["--json"])
            assert not fastpath.reuse_allowed(args), flag


# --------------------------------------------------------------------------- #
# run_check end-to-end: the payload contract is byte-identical across paths
# --------------------------------------------------------------------------- #


def _kubeconfig_for(tmp_path, port) -> str:
    kc = tmp_path / "kubeconfig"
    kc.write_text(
        "apiVersion: v1\ncurrent-context: c\n"
        "contexts:\n- name: c\n  context:\n    cluster: cl\n    user: u\n"
        "clusters:\n- name: cl\n  cluster:\n"
        f"    server: http://127.0.0.1:{port}\n"
        "users:\n- name: u\n  user:\n    token: tok\n"
    )
    return str(kc)


def _normalized(payload: dict) -> str:
    """The payload minus its per-round volatiles (trace identity, clocks,
    transport counters, resolved cluster identity) — everything else is
    the pinned byte-identical contract."""
    p = dict(payload)
    for key in ("trace_id", "timings_ms", "api_transport", "cluster",
                "cluster_source"):
        p.pop(key, None)
    return json.dumps(p, ensure_ascii=False, indent=2)


class TestRunCheckParity:
    def test_projection_oracle_and_offline_payloads_identical(
        self, tmp_path, monkeypatch
    ):
        from tpu_node_checker import checker, cli

        nodes = fx.tpu_v5e_256_slice(not_ready=3)
        server = fx.serve_http(fx.paged_nodelist_handler(nodes, []))
        try:
            kc = _kubeconfig_for(tmp_path, server.server_address[1])
            args = cli.parse_args(["--kubeconfig", kc, "--json"])
            checker.reset_client_cache()
            cold = checker.run_check(args)   # cold projected walk
            warm = checker.run_check(args)   # warm: tier-0 pages + reuse
            checker.reset_client_cache()
            monkeypatch.setenv("TNC_PROJECTION", "off")
            oracle = checker.run_check(args)  # every page through the oracle
            monkeypatch.delenv("TNC_PROJECTION")
            checker.reset_client_cache()
            # The pre-PR-shaped path: raw dicts through
            # select_accelerator_nodes (run_check's injected-nodes branch).
            offline = checker.run_check(args, nodes=nodes)
            assert (cold.exit_code == warm.exit_code == oracle.exit_code
                    == offline.exit_code)
            assert (_normalized(cold.payload) == _normalized(warm.payload)
                    == _normalized(oracle.payload)
                    == _normalized(offline.payload))
        finally:
            checker.reset_client_cache()
            server.shutdown()

    def test_warm_round_reuses_entries_by_reference(self, tmp_path):
        from tpu_node_checker import checker, cli

        nodes = fx.tpu_v5e_256_slice()
        server = fx.serve_http(fx.paged_nodelist_handler(nodes, []))
        try:
            kc = _kubeconfig_for(tmp_path, server.server_address[1])
            args = cli.parse_args(["--kubeconfig", kc, "--json"])
            checker.reset_client_cache()
            r1 = checker.run_check(args)
            r2 = checker.run_check(args)
            # Same entry dicts, same NodeInfo objects: the whole per-node
            # pipeline was reused by reference, not rebuilt equal.
            assert all(
                a is b for a, b in zip(r1.payload["nodes"], r2.payload["nodes"])
            )
            assert all(a is b for a, b in zip(r1.accel, r2.accel))
        finally:
            checker.reset_client_cache()
            server.shutdown()

    def test_attachment_flags_disable_reuse_not_projection(self, tmp_path):
        from tpu_node_checker import checker, cli

        nodes = fx.tpu_v5e_256_slice()
        server = fx.serve_http(fx.paged_nodelist_handler(nodes, []))
        try:
            kc = _kubeconfig_for(tmp_path, server.server_address[1])
            args = cli.parse_args(
                ["--kubeconfig", kc, "--history", str(tmp_path / "h.jsonl"),
                 "--json"]
            )
            checker.reset_client_cache()
            r1 = checker.run_check(args)
            r2 = checker.run_check(args)
            # NodeInfo carries per-round history state: entries must be
            # rebuilt fresh every round...
            assert all(
                a is not b
                for a, b in zip(r1.payload["nodes"], r2.payload["nodes"])
            )
            # ...but the page-level projection reuse still engages.
            client = checker._ROUND_CLIENT["client"]
            assert client.projector_stats["pages_unchanged"] > 0
        finally:
            checker.reset_client_cache()
            server.shutdown()


class TestEventsTruncationDegradation:
    def test_truncated_events_walk_stamps_degradation(self, tmp_path, capsys):
        import json as _json
        from http.server import BaseHTTPRequestHandler
        from urllib.parse import parse_qs, urlparse

        from tpu_node_checker import checker, cli

        nodes = fx.tpu_v5p_64_slice(not_ready=1)

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def do_GET(self):
                parsed = urlparse(self.path)
                q = parse_qs(parsed.query)
                if parsed.path == "/api/v1/nodes":
                    body = _json.dumps(fx.node_list(nodes)).encode()
                else:
                    # Events: ALWAYS another page — the walk can only end
                    # on its page budget.
                    token = int((q.get("continue") or ["0"])[0]) + 1
                    body = _json.dumps({
                        "items": [{"type": "Warning", "reason": f"R{token}",
                                   "message": "m",
                                   "lastTimestamp": "2026-08-03T10:00:00Z"}],
                        "metadata": {"continue": str(token)},
                    }).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):
                pass

        server = fx.serve_http(Handler)
        try:
            kc = _kubeconfig_for(tmp_path, server.server_address[1])
            checker.reset_client_cache()
            result = checker.run_check(
                cli.parse_args(["--kubeconfig", kc, "--node-events", "--json"])
            )
            sick_name = next(
                n["name"] for n in result.payload["nodes"] if not n["ready"]
            )
            assert result.payload["degraded"] is True
            assert result.payload["degradation"]["events_truncated"] == [
                sick_name
            ]
            assert result.payload["api_transport"]["list_truncated"] == {
                "events": 1
            }
            assert "newest events may be missing" in capsys.readouterr().err
            # The truncated walk still attached what it got.
            sick = next(
                n for n in result.payload["nodes"] if n["name"] == sick_name
            )
            assert sick["events"]
        finally:
            checker.reset_client_cache()
            server.shutdown()
