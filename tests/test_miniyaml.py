"""The stdlib YAML-subset parser (utils/miniyaml.py).

The contract under test: on every input it ACCEPTS, `safe_load_subset` must
agree exactly with `yaml.safe_load` (the differential tests below), and on
anything beyond the subset it must raise `UnsupportedYAML` — never silently
mis-parse — so cluster.py's PyYAML fallback keeps exotic kubeconfigs fully
correct while kubectl-style configs skip PyYAML's ~55 ms import.
"""

import pytest
import yaml

from tpu_node_checker.utils.miniyaml import UnsupportedYAML, safe_load_subset

KUBECTL_STYLE = """\
apiVersion: v1
kind: Config
current-context: gke_proj_zone_cluster
preferences: {}
clusters:
- cluster:
    certificate-authority-data: LS0tLS1CRUdJTg==
    server: https://34.1.2.3
  name: gke_proj_zone_cluster
contexts:
- context:
    cluster: gke_proj_zone_cluster
    user: gke_user
  name: gke_proj_zone_cluster
users:
- name: gke_user
  user:
    exec:
      apiVersion: client.authentication.k8s.io/v1beta1
      command: gke-gcloud-auth-plugin
      args: null
      provideClusterInfo: true
"""


class TestDifferentialAgainstPyYAML:
    """Everything the subset accepts must match yaml.safe_load exactly."""

    CASES = [
        KUBECTL_STYLE,
        "a: 1\nb: two\nc: 3.5\nd: true\ne: false\nf: null\ng: ~\n",
        "a: 'single quoted: colon'\nb: \"double \\\"q\\\" and\\ttab\"\n",
        "top:\n  mid:\n    leaf: v\n  sibling: 2\n",
        "items:\n- one\n- two\n- 3\n",
        "list:\n- name: a\n  value: 1\n- name: b\n  value: 2\n",
        "# leading comment\nkey: value  # trailing comment\n",
        "empty_map: {}\nempty_list: []\nempty_val:\n",
        "---\ndoc: with leading marker\n",
        "nested:\n- - 1\n  - 2\n- - 3\n",
        "mixed:\n- scalar\n- sub:\n    deep: true\n",
        "ints: -5\nplus: +7\nfloat: -2.5e3\nnot_num: 1.2.3\n",
        "weird key name: v\nkey2: a:b\n",
        "tokenFile: /var/run/secrets/token\ninsecure-skip-tls-verify: true\n",
        "",
        "   \n# only comments\n",
        # YAML 1.1 resolver case-sensitivity: mixed case stays a STRING.
        "a: tRue\nb: nO\nc: nUll\nd: yes\ne: Off\n",
        # Unicode digits and NBSP are content, never numbers/whitespace.
        "a: ٣\nk : 1\n",
        "hash_in_scalar: x#y\n",
        "crlf: value\r\n",
        # Signed dot-floats are STRINGS to PyYAML's 1.1 resolver; unsigned
        # dot-floats and digit-led signed floats are numbers.
        "negdot: -.5\nplusdot: +.5\ndot: .5\nsigned: -1.5\n",
    ]

    @pytest.mark.parametrize("text", CASES)
    def test_matches_pyyaml(self, text):
        assert safe_load_subset(text) == yaml.safe_load(text)

    def test_bench_kubeconfig_shape(self):
        text = KUBECTL_STYLE.replace("https://34.1.2.3", "http://127.0.0.1:5")
        doc = safe_load_subset(text)
        assert doc["clusters"][0]["cluster"]["server"] == "http://127.0.0.1:5"
        assert doc["users"][0]["user"]["exec"]["provideClusterInfo"] is True


class TestFuzzRoundtrip:
    """Property: for ANY document safe_dump writes in block style, the
    subset parser either refuses (fallback handles it) or agrees exactly
    with yaml.safe_load.  Silent disagreement is the one forbidden
    outcome."""

    def test_roundtrip_against_pyyaml(self):
        from hypothesis import given, settings, strategies as st

        scalars = st.one_of(
            st.none(),
            st.booleans(),
            st.integers(min_value=-10**6, max_value=10**6),
            st.floats(allow_nan=False, allow_infinity=False, width=32),
            st.text(max_size=12),
            # Numeric-looking strings: the scalar-resolver branches are
            # where silent divergence hides (signed dot-floats, octal,
            # sexagesimal, dates) — force the generator into them.
            st.from_regex(
                r"[+-]?[0-9:._eE+-]{1,10}", fullmatch=True
            ),
            st.from_regex(
                r"[0-9]{4}-[0-9]{2}-[0-9]{2}( [0-9:.]{1,8})?", fullmatch=True
            ),
        )
        docs = st.recursive(
            scalars,
            lambda children: st.one_of(
                st.lists(children, max_size=3),
                st.dictionaries(st.text(max_size=8), children, max_size=3),
            ),
            max_leaves=12,
        )

        @settings(max_examples=300, deadline=None)
        @given(docs)
        def check(doc):
            text = yaml.safe_dump(doc, default_flow_style=False,
                                  allow_unicode=True)
            try:
                parsed = safe_load_subset(text)
            except UnsupportedYAML:
                return  # refusing is always allowed — PyYAML handles it
            assert parsed == yaml.safe_load(text), text

        check()


class TestBailsInsteadOfGuessing:
    """Anything beyond the subset raises; silent mis-parse is the one
    failure mode this parser must never have."""

    BAIL = [
        "a: &anchor 1\nb: *anchor\n",  # anchors/aliases
        "a: |\n  block\n  scalar\n",  # literal block
        "a: >\n  folded\n",  # folded block
        "a: {flow: map}\n",  # non-empty flow mapping
        "a: [1, 2]\n",  # non-empty flow list
        "a: !!str tagged\n",  # tags
        "%YAML 1.2\na: b\n",  # directives
        "a: 1\n---\nb: 2\n",  # multi-document
        "? complex key\n: value\n",  # explicit key
        "\ta: tab indent\n",  # tabs
        "a: 'unterminated\n",  # quote spanning lines
        "just a scalar line\n",  # no key, not a list
        "a: <<: merge\n",
        "a: x'y # z' w\n",  # quote inside a plain scalar (comment ambiguity)
        "a: b: c\n",  # colon-space in a plain value (PyYAML parse error)
        "date: 2026-07-30\n",  # 1.1 timestamp resolution
        "ts: 2026-07-30 01:02:03\n",  # space-separated timestamp
        "oct: 010\nsex: 1:30\nsexf: 1:30.5\n",  # exotic numerics
        "a: -\n",  # bare dash: PyYAML parse error
        "a: =\n",  # the 1.1 "=" value type: PyYAML constructor error
        "a: ]\n",  # closing flow indicator: PyYAML parse error
        "a: }\n",
        "uf: 1_000.5\n",  # underscored float: 1000.5 to PyYAML's resolver
        "{}: v\n",  # non-scalar key: refuse, never a bare TypeError
    ]

    @pytest.mark.parametrize("text", BAIL)
    def test_raises_unsupported(self, text):
        with pytest.raises(UnsupportedYAML):
            safe_load_subset(text)


class TestClusterFallback:
    """cluster.py must accept BOTH styles: subset fast path and PyYAML
    fallback for flow-style configs."""

    def _config(self, tmp_path, text):
        p = tmp_path / "kubeconfig"
        p.write_text(text)
        from tpu_node_checker.cluster import load_kubeconfig

        return load_kubeconfig(str(p))

    def test_block_style_fast_path(self, tmp_path):
        cfg = self._config(
            tmp_path,
            "apiVersion: v1\ncurrent-context: c\n"
            "contexts:\n- name: c\n  context:\n    cluster: cl\n    user: u\n"
            "clusters:\n- name: cl\n  cluster:\n    server: https://h:6443\n"
            "users:\n- name: u\n  user:\n    token: tok\n",
        )
        assert cfg.server == "https://h:6443"
        assert cfg.token == "tok"

    def test_flow_style_falls_back_to_pyyaml(self, tmp_path):
        cfg = self._config(
            tmp_path,
            "apiVersion: v1\ncurrent-context: c\n"
            "contexts: [{name: c, context: {cluster: cl, user: u}}]\n"
            "clusters: [{name: cl, cluster: {server: 'https://h:6443'}}]\n"
            "users: [{name: u, user: {token: tok}}]\n",
        )
        assert cfg.server == "https://h:6443"
        assert cfg.token == "tok"

    def test_pyyaml_not_imported_on_fast_path(self, tmp_path):
        # The point of the subset parser: a kubectl-style config must not
        # pay PyYAML's import. Run in a fresh interpreter and check
        # sys.modules.
        import subprocess
        import sys

        p = tmp_path / "kubeconfig"
        p.write_text(
            "apiVersion: v1\ncurrent-context: c\n"
            "contexts:\n- name: c\n  context:\n    cluster: cl\n    user: u\n"
            "clusters:\n- name: cl\n  cluster:\n    server: https://h:6443\n"
            "users:\n- name: u\n  user:\n    token: tok\n"
        )
        code = (
            "import sys\n"
            "from tpu_node_checker.cluster import load_kubeconfig\n"
            f"cfg = load_kubeconfig({str(p)!r})\n"
            "assert cfg.token == 'tok'\n"
            "assert 'yaml' not in sys.modules, 'PyYAML imported on fast path'\n"
            "print('fast path ok')\n"
        )
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True
        )
        assert proc.returncode == 0, proc.stderr
        assert "fast path ok" in proc.stdout
