"""--cordon-failed auto-quarantine tests.

Node list comes from a fixture file (--nodes-json); the cordon PATCH goes to
a local fake API server via --kubeconfig, so both network surfaces of the
feature are exercised for real: request path, strategic-merge body, and
content type — plus every safety rail (cap, dry-run, probe-verdict-only,
already-cordoned, missing-report, PATCH failure is not fatal).
"""

import json
import time
from http.server import BaseHTTPRequestHandler

import pytest

from tests import fixtures as fx
from tpu_node_checker import checker, cli


@pytest.fixture
def fake_api(tmp_path):
    """Fake API server recording PATCHes + a kubeconfig pointing at it."""
    patches = []
    fail_with = {"status": None}

    class Handler(BaseHTTPRequestHandler):
        def do_PATCH(self):
            body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
            patches.append(
                {
                    "path": self.path,
                    "content_type": self.headers.get("Content-Type"),
                    "body": json.loads(body),
                }
            )
            status = fail_with["status"] or 200
            payload = b"{}"
            self.send_response(status)
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def log_message(self, *args):
            pass

    server = fx.serve_http(Handler)
    kubeconfig = tmp_path / "kubeconfig"
    kubeconfig.write_text(
        f"""
apiVersion: v1
kind: Config
current-context: t
contexts: [{{name: t, context: {{cluster: t, user: t}}}}]
clusters: [{{name: t, cluster: {{server: "http://127.0.0.1:{server.server_address[1]}"}}}}]
users: [{{name: t, user: {{token: tok}}}}]
"""
    )
    yield {"patches": patches, "kubeconfig": str(kubeconfig), "fail_with": fail_with}
    server.shutdown()


def _nodes_json(tmp_path, nodes):
    p = tmp_path / "nodes.json"
    p.write_text(json.dumps(fx.node_list(nodes)))
    return str(p)


def _probe_reports(tmp_path, verdicts):
    """Write per-host probe reports; verdicts = {hostname: ok_bool}."""
    d = tmp_path / "probes"
    d.mkdir()
    for host, ok in verdicts.items():
        (d / f"{host}.json").write_text(
            json.dumps(
                {
                    "ok": ok,
                    "level": "compute",
                    "hostname": host,
                    "written_at": time.time(),
                    "error": None if ok else "matmul numerics failed",
                }
            )
        )
    return str(d)


def _tpu_nodes(n=3, **kw):
    return [
        fx.make_node(
            f"tpu-{i}",
            allocatable={"google.com/tpu": "4"},
            labels={
                "cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice",
                "cloud.google.com/gke-nodepool": "p",
            },
            **kw,
        )
        for i in range(n)
    ]


class TestCordonFailed:
    def test_probe_failed_node_is_cordoned(self, tmp_path, fake_api, capsys):
        nodes = _tpu_nodes(3)
        reports = _probe_reports(
            tmp_path, {"tpu-0": True, "tpu-1": False, "tpu-2": True}
        )
        args = cli.parse_args(
            [
                "--nodes-json", _nodes_json(tmp_path, nodes),
                "--kubeconfig", fake_api["kubeconfig"],
                "--probe-results", reports,
                "--cordon-failed",
                "--json",
            ]
        )
        code = checker.one_shot(args)
        assert code == 0  # two healthy Ready nodes remain
        assert len(fake_api["patches"]) == 1
        patch = fake_api["patches"][0]
        assert patch["path"] == "/api/v1/nodes/tpu-1"
        assert patch["body"]["spec"] == {"unschedulable": True}
        # Cordon is stamped as OURS so --uncordon-recovered can identify it.
        anno = patch["body"]["metadata"]["annotations"]
        assert "tpu-node-checker.io/quarantined" in anno
        assert patch["content_type"] == "application/strategic-merge-patch+json"
        payload = json.loads(capsys.readouterr().out)
        assert payload["cordon"]["cordoned"] == ["tpu-1"]
        assert payload["cordon"]["dry_run"] is False
        # Offline node source, live PATCH traffic: the round's transport
        # telemetry must still surface (the on-demand resolved client).
        assert payload["api_transport"]["requests_sent"] >= 1

    def test_cap_limits_cordons_and_reports_rest(self, tmp_path, fake_api, capsys):
        nodes = _tpu_nodes(3)
        reports = _probe_reports(
            tmp_path, {"tpu-0": False, "tpu-1": False, "tpu-2": False}
        )
        args = cli.parse_args(
            [
                "--nodes-json", _nodes_json(tmp_path, nodes),
                "--kubeconfig", fake_api["kubeconfig"],
                "--probe-results", reports,
                "--cordon-failed",
                "--json",
            ]
        )
        checker.one_shot(args)
        assert len(fake_api["patches"]) == 1  # default --cordon-max 1
        payload = json.loads(capsys.readouterr().out)
        assert len(payload["cordon"]["cordoned"]) == 1
        assert len(payload["cordon"]["skipped_over_cap"]) == 2

    def test_raised_cap(self, tmp_path, fake_api, capsys):
        nodes = _tpu_nodes(3)
        reports = _probe_reports(
            tmp_path, {"tpu-0": False, "tpu-1": False, "tpu-2": True}
        )
        args = cli.parse_args(
            [
                "--nodes-json", _nodes_json(tmp_path, nodes),
                "--kubeconfig", fake_api["kubeconfig"],
                "--probe-results", reports,
                "--cordon-failed", "--cordon-max", "5",
                "--json",
            ]
        )
        checker.one_shot(args)
        assert len(fake_api["patches"]) == 2
        payload = json.loads(capsys.readouterr().out)
        assert payload["cordon"]["skipped_over_cap"] == []

    def test_dry_run_patches_nothing(self, tmp_path, fake_api, capsys):
        nodes = _tpu_nodes(2)
        reports = _probe_reports(tmp_path, {"tpu-0": False, "tpu-1": True})
        args = cli.parse_args(
            [
                "--nodes-json", _nodes_json(tmp_path, nodes),
                "--kubeconfig", fake_api["kubeconfig"],
                "--probe-results", reports,
                "--cordon-failed", "--cordon-dry-run",
                "--json",
            ]
        )
        checker.one_shot(args)
        assert fake_api["patches"] == []
        payload = json.loads(capsys.readouterr().out)
        assert payload["cordon"] == {
            "dry_run": True,
            "cordoned": ["tpu-0"],
            "failed": [],
            "already_cordoned": 0,
            "skipped_over_cap": [],
        }

    def test_already_cordoned_and_notready_nodes_skipped(
        self, tmp_path, fake_api, capsys
    ):
        nodes = [
            fx.make_node(
                "tpu-cordoned",
                unschedulable=True,
                allocatable={"google.com/tpu": "4"},
                labels={"cloud.google.com/gke-tpu-accelerator": "x"},
            ),
            fx.make_node(
                "tpu-notready",
                ready=False,
                allocatable={"google.com/tpu": "4"},
                labels={"cloud.google.com/gke-tpu-accelerator": "x"},
            ),
        ]
        reports = _probe_reports(
            tmp_path, {"tpu-cordoned": False, "tpu-notready": False}
        )
        args = cli.parse_args(
            [
                "--nodes-json", _nodes_json(tmp_path, nodes),
                "--kubeconfig", fake_api["kubeconfig"],
                "--probe-results", reports,
                "--cordon-failed",
                "--json",
            ]
        )
        checker.one_shot(args)
        assert fake_api["patches"] == []
        payload = json.loads(capsys.readouterr().out)
        assert payload["cordon"]["cordoned"] == []
        # cordoned state is surfaced per node
        by_name = {n["name"]: n for n in payload["nodes"]}
        assert by_name["tpu-cordoned"]["cordoned"] is True

    def test_missing_report_is_not_cordoned(self, tmp_path, fake_api, capsys):
        # --probe-results-required synthesizes level="missing" failures for
        # unreported hosts; an absent report is NOT evidence of dead chips.
        nodes = _tpu_nodes(2)
        reports = _probe_reports(tmp_path, {"tpu-0": True})  # tpu-1 never reported
        args = cli.parse_args(
            [
                "--nodes-json", _nodes_json(tmp_path, nodes),
                "--kubeconfig", fake_api["kubeconfig"],
                "--probe-results", reports, "--probe-results-required",
                "--cordon-failed",
                "--json",
            ]
        )
        code = checker.one_shot(args)
        assert fake_api["patches"] == []
        assert code == 0  # tpu-0 healthy

    def test_cordon_max_is_a_state_budget_not_a_rate(self, tmp_path, fake_api, capsys):
        # One node is ALREADY cordoned: with --cordon-max 1 the budget is
        # spent, so a new probe-failed node is NOT cordoned.  This is what
        # keeps a persistent regression under --watch from draining the pool
        # one node per round.
        nodes = [
            fx.make_node(
                "tpu-quarantined",
                unschedulable=True,
                allocatable={"google.com/tpu": "4"},
                labels={"cloud.google.com/gke-tpu-accelerator": "x"},
            ),
            *_tpu_nodes(2),
        ]
        reports = _probe_reports(tmp_path, {"tpu-0": False, "tpu-1": True})
        args = cli.parse_args(
            [
                "--nodes-json", _nodes_json(tmp_path, nodes),
                "--kubeconfig", fake_api["kubeconfig"],
                "--probe-results", reports,
                "--cordon-failed",
                "--json",
            ]
        )
        checker.one_shot(args)
        assert fake_api["patches"] == []
        payload = json.loads(capsys.readouterr().out)
        assert payload["cordon"]["already_cordoned"] == 1
        assert payload["cordon"]["skipped_over_cap"] == ["tpu-0"]

    def test_payload_nodes_reflect_post_cordon_state(self, tmp_path, fake_api, capsys):
        # The per-node entries must agree with the cordon report in the SAME
        # payload: the cordon phase runs before render.
        nodes = _tpu_nodes(2)
        reports = _probe_reports(tmp_path, {"tpu-0": False, "tpu-1": True})
        args = cli.parse_args(
            [
                "--nodes-json", _nodes_json(tmp_path, nodes),
                "--kubeconfig", fake_api["kubeconfig"],
                "--probe-results", reports,
                "--cordon-failed",
                "--json",
            ]
        )
        checker.one_shot(args)
        payload = json.loads(capsys.readouterr().out)
        assert payload["cordon"]["cordoned"] == ["tpu-0"]
        by_name = {n["name"]: n for n in payload["nodes"]}
        assert by_name["tpu-0"]["cordoned"] is True

    def test_patch_failure_is_reported_not_fatal(self, tmp_path, fake_api, capsys):
        fake_api["fail_with"]["status"] = 500
        nodes = _tpu_nodes(2)
        reports = _probe_reports(tmp_path, {"tpu-0": False, "tpu-1": True})
        args = cli.parse_args(
            [
                "--nodes-json", _nodes_json(tmp_path, nodes),
                "--kubeconfig", fake_api["kubeconfig"],
                "--probe-results", reports,
                "--cordon-failed",
                "--json",
            ]
        )
        code = checker.one_shot(args)
        assert code == 0  # the check's verdict stands
        payload = json.loads(capsys.readouterr().out)
        assert payload["cordon"]["cordoned"] == []
        assert payload["cordon"]["failed"][0]["node"] == "tpu-0"


def _quarantined_node(name, probe_ok):
    """A node cordoned by US (annotation present) with a given probe state."""
    node = fx.make_node(
        name,
        unschedulable=True,
        allocatable={"google.com/tpu": "4"},
        labels={"cloud.google.com/gke-tpu-accelerator": "x"},
    )
    node["metadata"]["annotations"] = {
        "tpu-node-checker.io/quarantined": "1700000000"
    }
    return node


class TestUncordonRecovered:
    def _args(self, tmp_path, fake_api, reports, *extra):
        return cli.parse_args(
            [
                "--nodes-json", tmp_path,
                "--kubeconfig", fake_api["kubeconfig"],
                "--probe-results", reports,
                "--uncordon-recovered",
                "--json",
                *extra,
            ]
        )

    def test_recovered_quarantined_node_is_uncordoned(
        self, tmp_path, fake_api, capsys
    ):
        nodes = [_quarantined_node("tpu-q", probe_ok=True)]
        reports = _probe_reports(tmp_path, {"tpu-q": True})
        args = self._args(_nodes_json(tmp_path, nodes), fake_api, reports)
        checker.one_shot(args)
        assert len(fake_api["patches"]) == 1
        patch = fake_api["patches"][0]
        assert patch["path"] == "/api/v1/nodes/tpu-q"
        assert patch["body"]["spec"] == {"unschedulable": False}
        # Strategic-merge null removes OUR annotation.
        assert patch["body"]["metadata"]["annotations"] == {
            "tpu-node-checker.io/quarantined": None
        }
        payload = json.loads(capsys.readouterr().out)
        assert payload["uncordon"]["uncordoned"] == ["tpu-q"]

    def test_human_cordon_never_touched(self, tmp_path, fake_api, capsys):
        # Cordoned but WITHOUT our annotation: a human did this; hands off
        # even with a passing probe.
        nodes = [
            fx.make_node(
                "tpu-human",
                unschedulable=True,
                allocatable={"google.com/tpu": "4"},
                labels={"cloud.google.com/gke-tpu-accelerator": "x"},
            )
        ]
        reports = _probe_reports(tmp_path, {"tpu-human": True})
        args = self._args(_nodes_json(tmp_path, nodes), fake_api, reports)
        checker.one_shot(args)
        assert fake_api["patches"] == []
        payload = json.loads(capsys.readouterr().out)
        assert payload["uncordon"]["uncordoned"] == []

    def test_still_failing_quarantine_stays(self, tmp_path, fake_api, capsys):
        nodes = [_quarantined_node("tpu-q", probe_ok=False)]
        reports = _probe_reports(tmp_path, {"tpu-q": False})
        args = self._args(_nodes_json(tmp_path, nodes), fake_api, reports)
        checker.one_shot(args)
        assert fake_api["patches"] == []

    def test_no_fresh_probe_no_uncordon(self, tmp_path, fake_api, capsys):
        # Quarantined node with NO probe report this round: no evidence of
        # recovery, no uncordon.
        nodes = [_quarantined_node("tpu-q", probe_ok=True)]
        reports = _probe_reports(tmp_path, {})
        args = self._args(_nodes_json(tmp_path, nodes), fake_api, reports)
        checker.one_shot(args)
        assert fake_api["patches"] == []

    def test_dry_run_shared_flag(self, tmp_path, fake_api, capsys):
        nodes = [_quarantined_node("tpu-q", probe_ok=True)]
        reports = _probe_reports(tmp_path, {"tpu-q": True})
        args = self._args(
            _nodes_json(tmp_path, nodes), fake_api, reports, "--cordon-dry-run"
        )
        checker.one_shot(args)
        assert fake_api["patches"] == []
        payload = json.loads(capsys.readouterr().out)
        assert payload["uncordon"] == {
            "dry_run": True,
            "uncordoned": ["tpu-q"],
            "failed": [],
            "stale_annotations_cleared": [],
        }

    def test_out_of_band_uncordon_clears_stale_annotation(
        self, tmp_path, fake_api, capsys
    ):
        # `kubectl uncordon` flips spec.unschedulable but leaves our
        # annotation behind; the checker must strip it — otherwise a later
        # HUMAN cordon on the node would be misattributed as ours and
        # auto-lifted.
        node = fx.make_node(
            "tpu-ooband",
            allocatable={"google.com/tpu": "4"},
            labels={"cloud.google.com/gke-tpu-accelerator": "x"},
        )  # schedulable again, annotation stale
        node["metadata"]["annotations"] = {
            "tpu-node-checker.io/quarantined": "1700000000"
        }
        reports = _probe_reports(tmp_path, {"tpu-ooband": True})
        args = self._args(_nodes_json(tmp_path, [node]), fake_api, reports)
        checker.one_shot(args)
        assert len(fake_api["patches"]) == 1
        patch = fake_api["patches"][0]
        assert patch["path"] == "/api/v1/nodes/tpu-ooband"
        # Annotation-only patch: spec is NOT touched.
        assert "spec" not in patch["body"]
        assert patch["body"]["metadata"]["annotations"] == {
            "tpu-node-checker.io/quarantined": None
        }
        payload = json.loads(capsys.readouterr().out)
        assert payload["uncordon"]["stale_annotations_cleared"] == ["tpu-ooband"]
        assert payload["uncordon"]["uncordoned"] == []

    def test_dry_run_previews_budget_consistently(self, tmp_path, fake_api, capsys):
        # Dry-run must preview the SAME decisions a real run would make:
        # the would-be-uncordoned node frees --cordon-max budget for the
        # new failure (cf. test_recovery_frees_cordon_budget_same_round).
        nodes = [_quarantined_node("tpu-q", probe_ok=True), *_tpu_nodes(1)]
        reports = _probe_reports(tmp_path, {"tpu-q": True, "tpu-0": False})
        args = cli.parse_args(
            [
                "--nodes-json", _nodes_json(tmp_path, nodes),
                "--kubeconfig", fake_api["kubeconfig"],
                "--probe-results", reports,
                "--uncordon-recovered", "--cordon-failed", "--cordon-dry-run",
                "--json",
            ]
        )
        checker.one_shot(args)
        assert fake_api["patches"] == []
        payload = json.loads(capsys.readouterr().out)
        assert payload["uncordon"]["uncordoned"] == ["tpu-q"]
        assert payload["cordon"]["cordoned"] == ["tpu-0"]
        assert payload["cordon"]["skipped_over_cap"] == []

    def test_recovery_frees_cordon_budget_same_round(
        self, tmp_path, fake_api, capsys
    ):
        # Uncordon runs first: a recovered quarantine frees --cordon-max
        # budget for this round's new failure.
        nodes = [_quarantined_node("tpu-q", probe_ok=True), *_tpu_nodes(1)]
        reports = _probe_reports(tmp_path, {"tpu-q": True, "tpu-0": False})
        args = cli.parse_args(
            [
                "--nodes-json", _nodes_json(tmp_path, nodes),
                "--kubeconfig", fake_api["kubeconfig"],
                "--probe-results", reports,
                "--uncordon-recovered", "--cordon-failed",
                "--json",
            ]
        )
        checker.one_shot(args)
        paths = [p["path"] for p in fake_api["patches"]]
        assert paths == ["/api/v1/nodes/tpu-q", "/api/v1/nodes/tpu-0"]
        payload = json.loads(capsys.readouterr().out)
        assert payload["uncordon"]["uncordoned"] == ["tpu-q"]
        assert payload["cordon"]["cordoned"] == ["tpu-0"]
        assert payload["cordon"]["skipped_over_cap"] == []


class TestCordonCli:
    def test_requires_probe_source(self, capsys):
        with pytest.raises(SystemExit):
            cli.parse_args(["--cordon-failed"])
        assert "requires --probe or --probe-results" in capsys.readouterr().err

    def test_dead_plugin_node_does_not_consume_budget(
        self, tmp_path, fake_api, capsys
    ):
        # A dead-device-plugin node (Ready, capacity shows chips, allocatable
        # zero) is already unschedulable for device pods; it must not claim
        # the cordon budget ahead of a genuinely dangerous node that still
        # advertises chips.
        nodes = [
            fx.make_node(
                "tpu-deadplugin",
                allocatable={"google.com/tpu": "0"},
                capacity={"cpu": "8", "google.com/tpu": "4"},
                labels={"cloud.google.com/gke-tpu-accelerator": "x"},
            ),
            *_tpu_nodes(1),
        ]
        reports = _probe_reports(tmp_path, {"tpu-deadplugin": False, "tpu-0": False})
        args = cli.parse_args(
            [
                "--nodes-json", _nodes_json(tmp_path, nodes),
                "--kubeconfig", fake_api["kubeconfig"],
                "--probe-results", reports,
                "--cordon-failed",
                "--json",
            ]
        )
        checker.one_shot(args)
        assert [p["path"] for p in fake_api["patches"]] == ["/api/v1/nodes/tpu-0"]

    @pytest.mark.parametrize(
        "argv, fragment",
        [
            (["--cordon-max", "1"], "requires --cordon-failed"),
            (["--cordon-max", "2"], "requires --cordon-failed"),
            (["--cordon-dry-run"], "requires --cordon-failed"),
            (["--probe", "--cordon-failed", "--cordon-max", "0"], "at least 1"),
            (
                ["--emit-probe", "x.json", "--probe-results", "d", "--cordon-failed"],
                "cannot be combined with --emit-probe",
            ),
        ],
    )
    def test_flag_validation(self, argv, fragment, capsys):
        with pytest.raises(SystemExit):
            cli.parse_args(argv)
        assert fragment in capsys.readouterr().err


class TestSlackCordonIntegration:
    def test_one_shot_slack_message_carries_cordon_lines(
        self, tmp_path, fake_api, monkeypatch, capsys
    ):
        from tpu_node_checker import notify

        sent = {}

        def fake_send(url, message, **kw):
            sent["message"] = message
            return True

        monkeypatch.setattr(notify, "send_slack_message", fake_send)
        nodes = _tpu_nodes(3)
        reports = _probe_reports(
            tmp_path, {"tpu-0": True, "tpu-1": False, "tpu-2": True}
        )
        args = cli.parse_args(
            [
                "--nodes-json", _nodes_json(tmp_path, nodes),
                "--kubeconfig", fake_api["kubeconfig"],
                "--probe-results", reports,
                "--cordon-failed",
                "--slack-webhook", "https://hooks.example/x",
                "--json",
            ]
        )
        checker.one_shot(args)
        assert "🚧 auto-cordoned (chip probe failed): `tpu-1`" in sent["message"]
