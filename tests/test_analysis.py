"""tnc-lint: engine unit tests, the seeded violation corpus, and the
repo-wide zero-findings gate.

Three layers:

* **engine units** — suppression parsing (same-line and standalone-above),
  the mandatory-reason and known-rule checks, JSON schema, exit codes;
* **seeded corpus** — ``tests/analysis_fixtures/repo`` is a miniature
  checkout where every rule has ``EXPECT[TNCxxx]`` markers on the exact
  lines it must fire and near-miss true negatives beside them; the test
  diffs the engine's findings against the markers in both directions;
* **the repo itself** — the tier-1 gate: zero unsuppressed findings over
  this checkout, every suppression carrying a reason.  This is the
  regression test for every invariant the rule table encodes AND for the
  drift fixed when the engine first ran (README flag-table rows, metric
  families missing from the metrics.py docstring index).
"""

import json
import re
from pathlib import Path

import pytest

from tpu_node_checker.analysis.engine import (
    JSON_SCHEMA_VERSION,
    extract_suppressions,
    run_project,
)
from tpu_node_checker.analysis.rules import ALL_RULES, RULE_SLUGS
from tpu_node_checker.analysis.rules.contracts import normalize_token

REPO_ROOT = Path(__file__).resolve().parent.parent
CORPUS_ROOT = Path(__file__).resolve().parent / "analysis_fixtures" / "repo"
_MARKER = re.compile(r"EXPECT\[(TNC\d+)\]")


class TestSuppressionParsing:
    def test_same_line_comment_parsed_with_reason(self):
        sups, meta = extract_suppressions(
            "x = 1  # tnc: allow-broad-except(probes report, never raise)\n"
        )
        assert meta == []
        (sup,) = sups
        assert sup.rule == "broad-except"
        assert sup.reason == "probes report, never raise"
        assert sup.line == 1
        assert sup.standalone is False

    def test_standalone_comment_marked_for_next_line(self):
        sups, _ = extract_suppressions(
            "# tnc: allow-unlocked-write(teardown path)\nx = 1\n"
        )
        (sup,) = sups
        assert sup.standalone is True and sup.line == 1

    def test_reason_is_mandatory(self):
        sups, meta = extract_suppressions("x = 1  # tnc: allow-broad-except()\n")
        assert sups == []  # an unexplained waiver never suppresses
        (m,) = meta
        assert m.code == "TNC002"
        assert "no reason" in m.message

    def test_unknown_rule_is_a_finding(self):
        sups, meta = extract_suppressions(
            "x = 1  # tnc: allow-everything(because)\n"
        )
        assert sups == []
        (m,) = meta
        assert m.code == "TNC003"

    def test_marker_inside_string_literal_is_not_a_suppression(self):
        # tokenize-based extraction: only real COMMENT tokens count.
        src = 's = "# tnc: allow-broad-except(not a comment)"\n'
        sups, meta = extract_suppressions(src)
        assert sups == [] and meta == []

    def test_every_registered_slug_is_stable_and_unique(self):
        slugs = [r.slug for r in ALL_RULES]
        codes = [r.code for r in ALL_RULES]
        assert len(set(slugs)) == len(slugs)
        assert len(set(codes)) == len(codes)
        assert all(re.fullmatch(r"TNC\d{3}", c) for c in codes)
        assert all(re.fullmatch(r"[a-z0-9-]+", s) for s in slugs)


class TestTokenNormalization:
    def test_label_selector_stripped(self):
        assert normalize_token('tpu_node_checker_nodes{state="total"}') == [
            "tpu_node_checker_nodes"
        ]

    def test_unmatched_brace_truncates(self):
        assert normalize_token("tpu_node_checker_nodes{state") == [
            "tpu_node_checker_nodes"
        ]

    def test_infix_alternation_expands(self):
        assert normalize_token(
            "tpu_node_checker_api_{connections_opened,requests}_total"
        ) == [
            "tpu_node_checker_api_connections_opened_total",
            "tpu_node_checker_api_requests_total",
        ]

    def test_bare_prefix_fragment_dropped(self):
        assert normalize_token("tpu_node_checker_") == []

    def test_wildcard_survives(self):
        assert normalize_token("tpu_node_checker_probe_*") == [
            "tpu_node_checker_probe_*"
        ]


class TestCliContract:
    def test_json_output_schema_and_exit_codes(self, capsys):
        from tpu_node_checker.analysis.__main__ import (
            EXIT_CLEAN,
            EXIT_FINDINGS,
            EXIT_USAGE,
            main,
        )

        rc = main(["--root", str(CORPUS_ROOT), "--format", "json"])
        assert rc == EXIT_FINDINGS  # the corpus exists to contain findings
        doc = json.loads(capsys.readouterr().out)
        assert doc["schema"] == JSON_SCHEMA_VERSION
        assert doc["files_scanned"] > 0
        for entry in doc["findings"] + doc["suppressed"]:
            assert set(entry) == {"rule", "code", "path", "line", "col",
                                  "message"}
            assert entry["rule"] in RULE_SLUGS or entry["code"] in (
                "TNC001", "TNC002", "TNC003"
            )
        # Ordering is stable: sorted by (path, line, col, code).
        keys = [(f["path"], f["line"], f["col"], f["code"])
                for f in doc["findings"]]
        assert keys == sorted(keys)

        assert main(["--rule", "no-such-rule"]) == EXIT_USAGE
        assert main(["--root", "/nonexistent-dir"]) == EXIT_USAGE
        assert main(["--list-rules"]) == EXIT_CLEAN
        listing = capsys.readouterr().out
        for rule in ALL_RULES:
            assert rule.code in listing and rule.slug in listing

    def test_single_rule_filter(self):
        report = run_project(str(CORPUS_ROOT), only_rules=["mutable-default"])
        codes = {f.code for f in report.findings}
        # Engine meta rules still run (they are part of the engine, not the
        # filter), so expect mutable-default plus at most TNC001-003.
        assert "TNC013" in codes
        assert codes <= {"TNC013", "TNC001", "TNC002", "TNC003"}

    def test_syntax_error_file_is_a_finding_not_a_crash(self, tmp_path):
        pkg = tmp_path / "tpu_node_checker"
        pkg.mkdir()
        (pkg / "broken.py").write_text("def f(:\n")
        report = run_project(str(tmp_path))
        (finding,) = [f for f in report.findings if f.code == "TNC001"]
        assert finding.path == "tpu_node_checker/broken.py"

    def test_rule_crash_exits_internal_not_findings(self, monkeypatch, capsys):
        # The CI corpus gate requires EXACTLY exit 1, so a crashed rule must
        # use a distinct code — a traceback impersonating "findings present"
        # would let every rule go blind while CI stays green.
        import tpu_node_checker.analysis.__main__ as main_mod

        def boom(root, only_rules=None):
            raise AttributeError("rule crashed mid-walk")

        monkeypatch.setattr(main_mod, "run_project", boom)
        rc = main_mod.main(["--root", str(CORPUS_ROOT)])
        assert rc == main_mod.EXIT_INTERNAL == 3
        assert "internal error" in capsys.readouterr().err

    def test_unused_suppression_reported_as_note_not_failure(self, tmp_path):
        pkg = tmp_path / "tpu_node_checker"
        pkg.mkdir()
        (pkg / "stale.py").write_text(
            "def f():\n"
            "    return 1  # tnc: allow-broad-except(the except was removed)\n"
        )
        report = run_project(str(tmp_path))
        assert report.findings == []  # informational, never a failure
        (unused,) = report.unused_suppressions
        assert unused["path"] == "tpu_node_checker/stale.py"
        assert unused["rule"] == "broad-except"
        assert unused["line"] == 2
        assert "unused_suppressions" in report.to_dict()

    def test_raise_systemexit_reports_exactly_once(self):
        report = run_project(str(CORPUS_ROOT), only_rules=["exit-code"])
        # Two seeded sites (sys.exit(3), raise SystemExit(2)) — one finding
        # each, never a duplicate for the Raise+Call pair.
        per_line = {}
        for f in report.findings:
            if f.code == "TNC015":
                per_line[f.line] = per_line.get(f.line, 0) + 1
        assert per_line and all(n == 1 for n in per_line.values()), per_line


class TestSeededCorpus:
    """Every rule fires exactly where the corpus says — and nowhere else."""

    def _expected(self):
        exp = set()
        for path in sorted(CORPUS_ROOT.rglob("*")):
            if not path.is_file() or "__pycache__" in path.parts:
                continue
            rel = path.relative_to(CORPUS_ROOT).as_posix()
            for lineno, line in enumerate(
                path.read_text().splitlines(), start=1
            ):
                for m in _MARKER.finditer(line):
                    exp.add((rel, lineno, m.group(1)))
        return exp

    def test_findings_match_markers_exactly(self):
        report = run_project(str(CORPUS_ROOT))
        # Virtual files (embedded *_SCRIPT constants) report as
        # "host.py#NAME" at host line numbers: fold back onto the host.
        got = {(f.path.split("#")[0], f.line, f.code)
               for f in report.findings}
        expected = self._expected()
        assert got - expected == set(), (
            f"false positives — findings on unmarked lines: "
            f"{sorted(got - expected)}"
        )
        assert expected - got == set(), (
            f"false negatives — marked lines without their finding: "
            f"{sorted(expected - got)}"
        )

    def test_every_rule_id_fires_in_the_corpus(self):
        report = run_project(str(CORPUS_ROOT))
        fired = {f.code for f in report.findings}
        fired |= {f.code for f in report.suppressed}
        registered = {r.code for r in ALL_RULES}
        assert registered <= fired, (
            f"rules with no seeded true positive: {sorted(registered - fired)}"
        )

    def test_corpus_suppressions_are_counted_not_reported(self):
        report = run_project(str(CORPUS_ROOT))
        suppressed = {(f.path.split("#")[0], f.code)
                      for f in report.suppressed}
        # One sanctioned seed per suppression-bearing rule family.
        assert ("tpu_node_checker/sample_broad.py", "TNC010") in suppressed
        assert ("tpu_node_checker/locked.py", "TNC101") in suppressed
        assert ("tests/sleepy.py", "TNC016") in suppressed
        assert ("tpu_node_checker/embedded.py", "TNC010") in suppressed
        # A graph-rule waiver on the ROOT function suppresses a finding
        # whose blocking site sits in ANOTHER file (storeio.py).
        assert ("tpu_node_checker/server/workers.py", "TNC111") in suppressed

    def test_embedded_script_findings_land_on_host_lines(self):
        report = run_project(str(CORPUS_ROOT))
        virt = [f for f in report.findings if "#" in f.path]
        (finding,) = virt
        assert finding.path == "tpu_node_checker/embedded.py#CHILD_SCRIPT"
        host = (CORPUS_ROOT / "tpu_node_checker" / "embedded.py").read_text()
        line = host.splitlines()[finding.line - 1]
        assert "except Exception" in line  # offset maps into the host file


class TestRepoIsClean:
    """The tier-1 gate: this checkout has zero unsuppressed findings."""

    @pytest.fixture()
    def repo_report(self):
        if not (REPO_ROOT / "tpu_node_checker" / "analysis").is_dir():
            pytest.skip("source tree not present (installed-wheel test run)")
        return run_project(str(REPO_ROOT))

    def test_zero_unsuppressed_findings(self, repo_report):
        assert repo_report.findings == [], (
            "tnc-lint found unsuppressed violations:\n"
            + "\n".join(
                f"{f.path}:{f.line}: {f.code}[{f.rule}] {f.message}"
                for f in repo_report.findings
            )
        )

    def test_every_suppression_carries_a_reason(self, repo_report):
        # Structural double-check: reasonless suppressions are TNC002
        # findings (covered above), so here assert the accepted ones all
        # carry non-trivial reasons — no "(x)" rubber stamps.
        from tpu_node_checker.analysis.engine import (
            extract_suppressions as extract,
        )

        for path in sorted((REPO_ROOT / "tpu_node_checker").rglob("*.py")):
            sups, _ = extract(path.read_text())
            for sup in sups:
                assert len(sup.reason) >= 10, (
                    f"{path}:{sup.line}: suppression reason too thin: "
                    f"{sup.reason!r}"
                )

    def test_repo_scan_covers_the_package_and_tests(self, repo_report):
        assert repo_report.files_scanned > 80
        # The probe child script rides along as a virtual file.
        # (run_project doesn't expose paths, so re-derive via the loader.)
        from tpu_node_checker.analysis.engine import load_project

        project = load_project(str(REPO_ROOT))
        assert "tpu_node_checker/probe/liveness.py#_CHILD_SCRIPT" in project.files
