"""Planned-disruption awareness (VERDICT r03 #5).

The reference collects taints but never interprets them
(check-gpu-node.py:207), so a GKE maintenance drain and a hardware fault
read identically.  These tests pin the interpretation: autoscaler /
impending-termination taints and spot labels become ``planned`` context on
nodes and slices — annotated across table, JSON, Slack, and metrics —
without ever changing a grade (exit codes are untouched: a drained slice is
still unusable for an SPMD job).
"""

import json

from tests import fixtures as fx
from tpu_node_checker import checker, cli, report
from tpu_node_checker.detect import extract_node_info, group_slices


def args_for(*argv):
    return cli.parse_args(list(argv))


def _tpu_node(name, ready=True, taints=None, labels=None):
    base_labels = {
        "cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice",
        "cloud.google.com/gke-tpu-topology": "4x4",
        "cloud.google.com/gke-nodepool": "v5e-pool",
    }
    base_labels.update(labels or {})
    return fx.make_node(
        name,
        ready=ready,
        allocatable={"google.com/tpu": "4"},
        labels=base_labels,
        taints=taints,
    )


MAINT_TAINT = {
    "key": "cloud.google.com/impending-node-termination",
    "value": None,
    "effect": "NoSchedule",
}
SCALE_TAINT = {
    "key": "ToBeDeletedByClusterAutoscaler",
    "value": "123",
    "effect": "NoSchedule",
}
CANDIDATE_TAINT = {
    "key": "DeletionCandidateOfClusterAutoscaler",
    "value": "123",
    "effect": "PreferNoSchedule",
}


class TestDetect:
    def test_taints_become_planned_disruptions(self):
        n = extract_node_info(_tpu_node("h", taints=[MAINT_TAINT, SCALE_TAINT]))
        assert n.planned_disruptions == (
            "impending-termination",
            "autoscaler-scale-down",
        )
        assert n.planned_word == "maintenance"  # termination outranks

    def test_autoscaler_only_is_scale_down(self):
        n = extract_node_info(_tpu_node("h", taints=[CANDIDATE_TAINT]))
        assert n.planned_disruptions == ("autoscaler-scale-down-candidate",)
        assert n.planned_word == "scale-down"

    def test_spot_label_is_interruptible(self):
        n = extract_node_info(
            _tpu_node("h", labels={"cloud.google.com/gke-spot": "true"})
        )
        assert n.interruptible is True
        assert n.planned_disruptions == ()
        assert n.to_dict()["planned"] == {
            "disruptions": [],
            "interruptible": True,
        }

    def test_garbage_taint_key_never_crashes(self):
        # API garbage: an unhashable taint key must not take down the
        # checker (the reference-era defensive-parsing contract).
        n = extract_node_info(
            _tpu_node("h", taints=[{"key": ["weird"], "effect": "NoSchedule"},
                                   MAINT_TAINT])
        )
        assert n.planned_disruptions == ("impending-termination",)

    def test_ordinary_taints_are_not_planned(self):
        n = extract_node_info(
            _tpu_node(
                "h",
                taints=[{"key": "node.kubernetes.io/not-ready",
                         "value": None, "effect": "NoExecute"}],
            )
        )
        assert n.planned_disruptions == ()
        assert "planned" not in n.to_dict()

    def test_grading_is_untouched(self):
        # Planned context must NEVER change readiness: a draining Ready node
        # still counts Ready, a draining NotReady node still fails.
        n = extract_node_info(_tpu_node("h", ready=True, taints=[MAINT_TAINT]))
        assert n.ready and n.effectively_ready
        n = extract_node_info(_tpu_node("h", ready=False, taints=[MAINT_TAINT]))
        assert not n.ready


class TestSliceContext:
    def _slice(self, sick_taints, all_sick_planned=True):
        nodes = [_tpu_node(f"h{i}") for i in range(3)]
        nodes.append(
            _tpu_node("h3", ready=False, taints=sick_taints)
        )
        return group_slices([extract_node_info(n) for n in nodes])[0]

    def test_all_sick_hosts_planned_annotates(self):
        s = self._slice([MAINT_TAINT])
        assert not s.complete
        assert s.planned_context == "maintenance"
        assert s.to_dict()["planned_context"] == "maintenance"

    def test_unplanned_sick_host_stays_bare_degraded(self):
        # A real fault may hide behind a drain: one sick host with no
        # planned signal keeps the slice an incident.
        s = self._slice(None)
        assert s.planned_context is None
        assert "planned_context" not in s.to_dict()

    def test_complete_slice_has_no_context(self):
        nodes = [_tpu_node(f"h{i}", taints=[MAINT_TAINT]) for i in range(4)]
        s = group_slices([extract_node_info(n) for n in nodes])[0]
        assert s.complete and s.planned_context is None

    def test_failed_probe_is_never_excused(self):
        # A Ready node with dead chips AND a maintenance taint: the drain
        # does not explain dead silicon — a real fault must not hide
        # behind it.
        from tpu_node_checker.detect import extract_node_info as _e

        nodes = [_e(_tpu_node(f"h{i}")) for i in range(3)]
        sick = _e(_tpu_node("h3", taints=[MAINT_TAINT]))
        sick.probe = {"ok": False, "level": "compute", "error": "MXU dead"}
        assert sick.sickness_planned is False
        s = group_slices(nodes + [sick])[0]
        assert not s.complete and s.planned_context is None

    def test_soft_candidate_taint_excuses_nothing(self):
        # DeletionCandidateOfClusterAutoscaler marks an underutilized node
        # that is still Ready/schedulable; a NotReady node carrying only
        # that soft mark is a fault, not a drain.
        n = extract_node_info(
            _tpu_node("h", ready=False, taints=[CANDIDATE_TAINT])
        )
        assert n.sickness_planned is False
        nodes = [extract_node_info(_tpu_node(f"h{i}")) for i in range(3)]
        s = group_slices(nodes + [n])[0]
        assert s.planned_context is None

    def test_missing_hosts_defeat_the_annotation(self):
        # A drained host that got DELETED cannot explain anything: 3 of 4
        # expected hosts present, all Ready → incomplete, no context.
        nodes = [_tpu_node(f"h{i}") for i in range(3)]
        s = group_slices([extract_node_info(n) for n in nodes])[0]
        assert not s.complete
        assert s.planned_context is None


class TestSurfaces:
    def _cluster(self):
        nodes = [_tpu_node(f"h{i}") for i in range(3)]
        nodes.append(_tpu_node("h3", ready=False, taints=[MAINT_TAINT]))
        return nodes

    def test_table_annotates_status(self, capsys):
        code = checker.one_shot(args_for(), nodes=self._cluster())
        assert code == 0  # grading untouched: 3 Ready hosts
        out = capsys.readouterr().out
        assert "NotReady (maintenance)" in out
        assert "DEGRADED (maintenance)" in out  # slice table

    def test_json_carries_planned(self, capsys):
        code = checker.one_shot(args_for("--json"), nodes=self._cluster())
        assert code == 0
        payload = json.loads(capsys.readouterr().out)
        sick = [n for n in payload["nodes"] if n["name"] == "h3"][0]
        assert sick["planned"]["disruptions"] == ["impending-termination"]
        assert payload["slices"][0]["planned_context"] == "maintenance"

    def test_slack_annotates_degraded_and_summarizes(self):
        infos = [extract_node_info(n) for n in self._cluster()]
        slices = group_slices(infos)
        ready = [n for n in infos if n.effectively_ready]
        msg = report.format_slack_message(infos, ready, slices, healthy=False)
        assert msg.startswith(
            "⚠️ *Accelerator node check: degraded (planned maintenance "
            "in progress)*"
        )
        assert "DEGRADED (maintenance)" in msg
        assert "planned disruption" in msg
        assert "maintenance" in msg

    def test_one_unexplained_fault_keeps_the_incident_header(self):
        nodes = self._cluster()
        nodes.append(_tpu_node("h4", ready=False))  # no planned signal
        infos = [extract_node_info(n) for n in nodes]
        slices = group_slices(infos)
        ready = [n for n in infos if n.effectively_ready]
        msg = report.format_slack_message(infos, ready, slices, healthy=False)
        assert "planned maintenance in progress" not in msg.splitlines()[0]
        assert "slice incomplete or chip probe failed" in msg.splitlines()[0]

    def test_unplanned_outage_slack_has_no_maintenance_words(self):
        nodes = [_tpu_node(f"h{i}") for i in range(3)]
        nodes.append(_tpu_node("h3", ready=False))
        infos = [extract_node_info(n) for n in nodes]
        slices = group_slices(infos)
        ready = [n for n in infos if n.effectively_ready]
        msg = report.format_slack_message(infos, ready, slices, healthy=False)
        assert "maintenance" not in msg
        assert "planned disruption" not in msg

    def test_metrics_family(self):
        result = checker.run_check(args_for("--json"), nodes=self._cluster())
        from tpu_node_checker.metrics import render_metrics

        text = render_metrics(result)
        assert (
            'tpu_node_checker_planned_disruption_nodes{reason="impending-termination"} 1'
            in text
        )

    def test_planned_round_flagged_in_state_log(self, tmp_path, capsys):
        # A degraded round where EVERY sick node is under planned disruption
        # logs planned=true; one unexplained sick node keeps it unplanned.
        log = tmp_path / "log.jsonl"
        nodes = [_tpu_node(f"h{i}") for i in range(3)]
        nodes.append(_tpu_node("h3", ready=False, taints=[MAINT_TAINT]))
        code = checker.one_shot(
            args_for("--strict-slices", "--log-jsonl", str(log)), nodes=nodes
        )
        assert code == 3
        assert json.loads(log.read_text().splitlines()[-1])["planned"] is True
        nodes.append(_tpu_node("h4", ready=False))  # unexplained fault
        checker.one_shot(
            args_for("--strict-slices", "--log-jsonl", str(log)), nodes=nodes
        )
        assert "planned" not in json.loads(log.read_text().splitlines()[-1])
        capsys.readouterr()

    def test_probe_failed_round_never_planned(self, tmp_path, capsys):
        # A maintenance-tainted host whose probe REPORT says dead chips:
        # the round must stay unplanned in the trend math.
        log = tmp_path / "log.jsonl"
        reports = tmp_path / "reports"
        reports.mkdir()
        nodes = [_tpu_node(f"h{i}") for i in range(3)]
        nodes.append(_tpu_node("h3", taints=[MAINT_TAINT]))
        (reports / "h3.json").write_text(
            json.dumps({"ok": False, "hostname": "h3", "level": "compute"})
        )
        code = checker.one_shot(
            args_for(
                "--strict-slices", "--probe-results", str(reports),
                "--log-jsonl", str(log),
            ),
            nodes=nodes,
        )
        assert code == 3
        assert "planned" not in json.loads(log.read_text().splitlines()[-1])
        capsys.readouterr()

    def test_candidate_only_round_never_planned(self, tmp_path, capsys):
        log = tmp_path / "log.jsonl"
        nodes = [_tpu_node(f"h{i}") for i in range(3)]
        nodes.append(_tpu_node("h3", ready=False, taints=[CANDIDATE_TAINT]))
        code = checker.one_shot(
            args_for("--strict-slices", "--log-jsonl", str(log)), nodes=nodes
        )
        assert code == 3
        assert "planned" not in json.loads(log.read_text().splitlines()[-1])
        capsys.readouterr()

    def test_trend_splits_planned_outage(self, tmp_path, capsys):
        t0 = 1_700_000_000
        entries = [
            {"ts": t0, "exit_code": 0},
            {"ts": t0 + 60, "exit_code": 3, "planned": True},
            {"ts": t0 + 120, "exit_code": 0},
            {"ts": t0 + 180, "exit_code": 3},  # unplanned
        ]
        log = tmp_path / "t.jsonl"
        log.write_text("\n".join(json.dumps(e) for e in entries) + "\n")
        assert cli.main(["--trend", str(log), "--json"]) == 0
        s = json.loads(capsys.readouterr().out)
        # 3 intervals of 60s + final median 60s: ok=120s, planned bad=60s,
        # unplanned bad=60s → unplanned availability 120/(240-60) = 66.67%.
        assert s["planned_outage_s"] == 60.0
        assert s["unplanned_availability_pct"] == 66.67
        assert cli.main(["--trend", str(log)]) == 0
        out = capsys.readouterr().out
        assert "excluding 60.0s planned maintenance" in out

    def test_trend_causes_note_planned(self, tmp_path, capsys):
        log = tmp_path / "log.jsonl"
        nodes = [_tpu_node(f"h{i}", ready=(i < 2)) for i in range(4)]
        for n in nodes[2:]:
            n["spec"]["taints"] = [MAINT_TAINT]
        code = checker.one_shot(
            args_for("--strict-slices", "--log-jsonl", str(log)), nodes=nodes
        )
        assert code == 3
        entry = json.loads(log.read_text().splitlines()[-1])
        assert any("(maintenance)" in c for c in entry["causes"])
        capsys.readouterr()
