"""Watch-stream incremental rounds (--watch-stream).

The contract under test (DESIGN.md §12):

* the transport decodes chunked watch frames off the live socket, and a
  410 at connect surfaces as :class:`~tpu_node_checker.cluster.WatchGone`;
* the node cache is O(changes): heartbeat-shaped MODIFIED events (grading
  view unchanged) advance the resourceVersion without dirtying the node;
* a tick with zero pending changes returns the cached round untouched —
  and with pending changes re-grades ONLY the changed nodes, with the
  payload matching what a poll-mode ``run_check`` over the same fleet
  produces;
* stream loss (clean EOF, reset, in-band 410 replay) triggers exactly one
  clean relist, visible in ``watch_relists_total`` and on the fixture
  server's LIST log; a relist that cannot complete raises like a failed
  poll round (the breaker path is shared, not duplicated);
* FSM evidence semantics: a silent stream banks NOTHING — neither healthy
  rounds toward --uncordon-after nor bad rounds toward --cordon-after.

Wall-clock policy: waits on the REAL stream are bounded polls, annotated;
nothing sleeps for pacing.
"""

import json
import time

import pytest

from tests import fixtures as fx
from tpu_node_checker import checker, cli, cluster
from tpu_node_checker.watchstream import NodeCache, StreamRoundEngine, grading_view

WALL_CLOCK_BUDGET_S = 30.0


@pytest.fixture(autouse=True)
def _wall_clock_guard():
    t0 = time.perf_counter()
    yield
    elapsed = time.perf_counter() - t0
    assert elapsed < WALL_CLOCK_BUDGET_S, (
        f"watch-stream test burned {elapsed:.1f}s of wall-clock — a stream "
        "wait leaked past its bound"
    )


def _write_kubeconfig(tmp_path, port) -> str:
    path = tmp_path / "kubeconfig"
    path.write_text(
        f"""\
apiVersion: v1
kind: Config
current-context: t
contexts:
- name: t
  context:
    cluster: t
    user: t
clusters:
- name: t
  cluster:
    server: http://127.0.0.1:{port}
users:
- name: t
  user:
    token: test-token
"""
    )
    return str(path)


def _tpu_node(name, ready=True):
    return fx.make_node(
        name,
        ready=ready,
        allocatable={"google.com/tpu": "4"},
        labels={
            "cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice",
            "cloud.google.com/gke-tpu-topology": "2x2",
            "cloud.google.com/gke-nodepool": "ws-pool",
        },
        taints=[fx.TPU_TAINT],
    )


def _wait(predicate, timeout=5.0, what="stream delivery"):
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        if predicate():
            return
        time.sleep(0.01)  # tnc: allow-test-wall-clock(bounded poll for a REAL watch socket to deliver frames to the reader thread; no clock to fake in the TCP stack)
    raise AssertionError(f"timed out waiting for {what}")


@pytest.fixture
def stream_world(tmp_path):
    """Fixture server + engine over a 4-host TPU slice, torn down after."""
    nodes = [_tpu_node(f"ws-{i}") for i in range(4)]
    script = fx.WatchScript([{"live": True}])
    list_requests: list = []
    server = fx.serve_http(
        fx.watch_nodelist_handler(
            nodes, script, resource_version="100", list_requests=list_requests
        )
    )
    kubeconfig = _write_kubeconfig(tmp_path, server.server_address[1])
    engines = []

    def make_engine(*extra):
        args = cli.parse_args(
            ["--kubeconfig", kubeconfig, "--watch", "5", "--watch-stream",
             "--json", *extra]
        )
        engine = StreamRoundEngine(args)
        engines.append(engine)
        return engine

    world = {
        "nodes": nodes,
        "script": script,
        "server": server,
        "kubeconfig": kubeconfig,
        "list_requests": list_requests,
        "make_engine": make_engine,
    }
    try:
        yield world
    finally:
        for engine in engines:
            engine.close()
        script.close()
        server.shutdown()
        checker.reset_client_cache()


class TestGradingView:
    def test_heartbeat_only_change_is_invisible(self):
        a = _tpu_node("n1")
        b = json.loads(json.dumps(a))
        b["status"]["conditions"][1]["lastHeartbeatTime"] = "2026-08-03T00:00:00Z"
        b["metadata"]["resourceVersion"] = "999"
        assert grading_view(a) == grading_view(b)

    @pytest.mark.parametrize(
        "mutate",
        [
            lambda n: n["status"]["conditions"][1].update(status="False"),
            lambda n: n["metadata"]["labels"].update(extra="x"),
            lambda n: n["spec"].update(unschedulable=True),
            lambda n: n["status"]["allocatable"].update({"google.com/tpu": "0"}),
            lambda n: n["status"]["conditions"][1].update(reason="KubeletNotReady"),
        ],
    )
    def test_grading_input_changes_are_visible(self, mutate):
        a = _tpu_node("n1")
        b = json.loads(json.dumps(a))
        mutate(b)
        assert grading_view(a) != grading_view(b)


class TestNodeCache:
    def test_seed_then_reseed_diffs(self):
        cache = NodeCache()
        cache.seed([_tpu_node("a"), _tpu_node("b")], "1")
        changed, removed = cache.drain()
        assert set(changed) == {"a", "b"} and not removed
        # Identical reseed: nothing dirties.
        cache.seed([_tpu_node("a"), _tpu_node("b")], "2")
        changed, removed = cache.drain()
        assert not changed and not removed
        assert cache.resource_version == "2"
        # One node sickens, one departs, one arrives.
        cache.seed([_tpu_node("a", ready=False), _tpu_node("c")], "3")
        changed, removed = cache.drain()
        assert set(changed) == {"a", "c"}
        assert removed == frozenset({"b"})

    def test_apply_modified_heartbeat_does_not_dirty(self):
        cache = NodeCache()
        cache.seed([_tpu_node("a")], "1")
        cache.drain()
        hb = _tpu_node("a")
        hb["metadata"]["resourceVersion"] = "2"
        hb["status"]["conditions"][1]["lastHeartbeatTime"] = "t"
        cache.apply("MODIFIED", hb)
        assert cache.pending() == 0
        assert cache.resource_version == "2"

    def test_apply_delete_and_bookmark(self):
        cache = NodeCache()
        cache.seed([_tpu_node("a")], "1")
        cache.drain()
        cache.apply("DELETED", {"metadata": {"name": "a", "resourceVersion": "5"}})
        changed, removed = cache.drain()
        assert not changed and removed == frozenset({"a"})
        cache.note_bookmark({"metadata": {"resourceVersion": "9"}})
        assert cache.resource_version == "9"
        assert cache.pending() == 0

    def test_delete_then_readd_is_changed_not_removed(self):
        cache = NodeCache()
        cache.seed([_tpu_node("a")], "1")
        cache.drain()
        cache.apply("DELETED", {"metadata": {"name": "a"}})
        cache.apply("ADDED", _tpu_node("a", ready=False))
        changed, removed = cache.drain()
        assert set(changed) == {"a"} and not removed

    def _projected(self, nodes, rv):
        import json

        from tpu_node_checker import fastpath

        class _Resp:
            content = json.dumps({"items": nodes}).encode()

        projector = fastpath.ListProjector()
        items, _meta = projector.decode_page(_Resp(), 0)
        return fastpath.ProjectedFleet(items, rv, projector.reuse)

    def test_projected_seed_diffs_like_a_raw_seed(self):
        cache = NodeCache()
        cache.seed(self._projected([_tpu_node("a"), _tpu_node("b")], "1"), "1")
        changed, removed = cache.drain()
        assert set(changed) == {"a", "b"} and not removed
        cache.seed(
            self._projected([_tpu_node("a", ready=False), _tpu_node("c")], "2"),
            "2",
        )
        changed, removed = cache.drain()
        assert set(changed) == {"a", "c"}
        assert removed == frozenset({"b"})
        # The cached docs are the PRUNED grading views, and they grade
        # exactly like the raw objects they project (extract parity).
        assert "managedFields" not in changed["a"].get("metadata", {})

    def test_digests_agree_across_raw_seed_event_and_projected_relist(self):
        # The cross-type invariant the relist fast path rests on: a raw
        # LIST seed, a raw watch event, and a projected relist of the SAME
        # grading state all hash to the same content address — so a
        # post-loss relist dirties nothing a quiet stream didn't change.
        cache = NodeCache()
        cache.seed([_tpu_node("a"), _tpu_node("b")], "1")
        cache.drain()
        # Heartbeat-only MODIFIED event: cache updated, nothing dirty.
        hb = _tpu_node("a")
        hb["status"]["conditions"][1]["lastHeartbeatTime"] = "t2"
        hb["spec"]["podCIDR"] = "10.0.0.0/24"  # non-grading spec churn
        cache.apply("MODIFIED", hb)
        assert cache.pending() == 0
        # Projected relist of the unchanged fleet: still nothing dirty.
        cache.seed(self._projected([_tpu_node("a"), _tpu_node("b")], "3"), "3")
        assert cache.pending() == 0
        # And a real grading change via relist IS seen.
        cache.seed(
            self._projected([_tpu_node("a", ready=False), _tpu_node("b")], "4"),
            "4",
        )
        changed, removed = cache.drain()
        assert set(changed) == {"a"} and not removed


class TestWatchTransport:
    def test_watch_nodes_decodes_frames(self, stream_world):
        cfg = cluster.ClusterConfig(
            server=f"http://127.0.0.1:{stream_world['server'].server_address[1]}"
        )
        client = cluster.KubeClient(cfg)
        try:
            stream = client.watch_nodes("100")
            stream_world["script"].push(
                fx.watch_event("ADDED", _tpu_node("ws-new"), resource_version="101")
            )
            stream_world["script"].push(fx.watch_bookmark("102"))
            stream_world["script"].push(None)
            events = [json.loads(line) for line in stream.iter_lines()]
            assert [e["type"] for e in events] == ["ADDED", "BOOKMARK"]
            assert events[0]["object"]["metadata"]["name"] == "ws-new"
        finally:
            client.close()

    def test_connect_410_raises_watch_gone(self, stream_world):
        stream_world["script"]._stanzas.insert(0, {"status": 410})
        cfg = cluster.ClusterConfig(
            server=f"http://127.0.0.1:{stream_world['server'].server_address[1]}"
        )
        client = cluster.KubeClient(cfg)
        try:
            with pytest.raises(cluster.WatchGone):
                client.watch_nodes("100")
        finally:
            client.close()

    def test_list_nodes_with_rv_returns_resource_version(self, stream_world):
        cfg = cluster.ClusterConfig(
            server=f"http://127.0.0.1:{stream_world['server'].server_address[1]}"
        )
        client = cluster.KubeClient(cfg)
        try:
            items, rv = client.list_nodes_with_rv()
            assert len(items) == 4
            assert rv == "100"
        finally:
            client.close()


class TestStreamEngine:
    def test_seed_tick_matches_poll_mode_payload(self, stream_world):
        engine = stream_world["make_engine"]()
        result, delta = engine.tick()
        assert delta == frozenset(f"ws-{i}" for i in range(4))
        poll = checker.run_check(
            cli.parse_args(["--json"]),
            nodes=[json.loads(json.dumps(n)) for n in stream_world["nodes"]],
        )
        assert result.exit_code == poll.exit_code == 0
        assert result.payload["nodes"] == poll.payload["nodes"]
        assert result.payload["slices"] == poll.payload["slices"]
        assert result.payload["total_chips"] == poll.payload["total_chips"] == 16
        # Exactly one relist: the seed.
        assert result.payload["watch_stream"]["relists_total"] == {"seed": 1}

    def test_steady_tick_is_a_noop_with_fresh_transitions(self, stream_world):
        engine = stream_world["make_engine"]()
        first, _ = engine.tick()
        lists_before = len(stream_world["list_requests"])
        result, delta = engine.tick()
        assert delta == frozenset()
        assert result.exit_code == first.exit_code
        # Heavy sub-objects are shared by reference; the top-level payload
        # is fresh (published snapshots must never see mutation).
        assert result.payload["nodes"] is first.payload["nodes"]
        assert result.payload is not first.payload
        # No LIST traffic on a steady tick.
        assert len(stream_world["list_requests"]) == lists_before

    def test_steady_ticks_produce_rollups_with_analytics(
        self, stream_world, tmp_path
    ):
        # PR 19 acceptance: before, --analytics + --watch-stream was
        # rejected outright, so a steady streamed fleet produced ZERO
        # roll-ups.  Now every tick — steady included — folds verdicts
        # into the segment store and duration samples into the fleet
        # sketches.
        engine = stream_world["make_engine"](
            "--history", str(tmp_path / "h.jsonl"),
            "--analytics", str(tmp_path / "ana"),
        )
        first, _ = engine.tick()
        assert first.analytics_docs is not None
        assert set(first.analytics_docs) == {"slo", "offenders", "flaps"}
        samples_first = first.payload["analytics"]["sketch_samples"]
        steady = None
        for _ in range(3):
            steady, delta = engine.tick()
            assert delta == frozenset()
        assert steady.analytics_docs is not None
        slo = steady.analytics_docs["slo"]
        assert slo["fleet"]["nodes"] == 4
        assert slo["source"] == "rollups"
        assert slo["sketch_alpha"] == pytest.approx(0.01)
        # Each steady tick folded a round-duration sample into the
        # reserved fleet stream — the previously-zero evidence.
        samples_steady = steady.payload["analytics"]["sketch_samples"]
        assert samples_steady.get("round_ms", 0) >= (
            samples_first.get("round_ms", 0) + 3
        )
        # Steady verdicts reached the per-node running aggregates too:
        # four healthy rounds per node, one per tick.
        stats = checker._build_analytics(engine.args)["store"].node_stats
        assert stats["ws-0"]["n"] >= 4
        assert stats["ws-0"]["ok"] == stats["ws-0"]["n"]

    def test_event_flips_grade_and_back(self, stream_world):
        engine = stream_world["make_engine"]()
        engine.tick()
        # All four hosts NotReady -> exit 3.
        for i in range(4):
            stream_world["script"].push(
                fx.watch_event(
                    "MODIFIED", _tpu_node(f"ws-{i}", ready=False),
                    resource_version=str(200 + i),
                )
            )
        _wait(lambda: engine.cache.pending() >= 4)
        result, delta = engine.tick()
        assert delta == frozenset(f"ws-{i}" for i in range(4))
        assert result.exit_code == checker.EXIT_NONE_READY
        assert result.payload["ready_chips"] == 0
        # Recovery event for one host: exit still 3, delta is just that one.
        stream_world["script"].push(
            fx.watch_event("MODIFIED", _tpu_node("ws-2"), resource_version="210")
        )
        _wait(lambda: engine.cache.pending() >= 1)
        result, delta = engine.tick()
        assert delta == frozenset({"ws-2"})
        assert result.payload["ready_chips"] == 4

    def test_deleted_node_leaves_the_payload(self, stream_world):
        engine = stream_world["make_engine"]()
        engine.tick()
        stream_world["script"].push(
            fx.watch_event("DELETED", _tpu_node("ws-3"), resource_version="300")
        )
        _wait(lambda: engine.cache.pending() >= 1)
        result, delta = engine.tick()
        assert "ws-3" in delta
        assert result.payload["total_nodes"] == 3
        assert all(n["name"] != "ws-3" for n in result.payload["nodes"])

    def test_stream_end_triggers_exactly_one_relist(self, stream_world):
        engine = stream_world["make_engine"]()
        engine.tick()
        lists_before = len(stream_world["list_requests"])
        stream_world["script"].push(None)  # server ends the stream cleanly
        _wait(lambda: not engine.stream_alive(), what="worker exit")
        result, _ = engine.tick()
        assert result.payload["watch_stream"]["relists_total"] == {
            "seed": 1, "stream_end": 1,
        }
        assert len(stream_world["list_requests"]) == lists_before + 1
        # And the stream is live again: steady ticks relist no further.
        result, delta = engine.tick()
        assert delta == frozenset()
        assert len(stream_world["list_requests"]) == lists_before + 1

    def test_failed_reconnect_does_not_relist_again(self, stream_world):
        # One stream loss = ONE relist, even when the reconnect itself
        # fails for a few rounds: the dead worker's exit reason is consumed
        # by the first reconnect attempt, and later attempts retry only the
        # watch connect (the cache's resourceVersion is still the relist's;
        # a stale one would surface as 410 and earn its own relist).
        engine = stream_world["make_engine"]("--retry-budget", "0")
        engine.tick()
        lists_before = len(stream_world["list_requests"])
        stream_world["script"].push(None)
        _wait(lambda: not engine.stream_alive(), what="worker exit")
        stream_world["script"]._stanzas.insert(0, {"status": 500})
        with pytest.raises(Exception):
            engine.tick()  # relists once, then the watch connect 500s
        lists_after_failure = len(stream_world["list_requests"])
        assert lists_after_failure == lists_before + 1
        result, _ = engine.tick()  # connect succeeds; NO second LIST
        assert len(stream_world["list_requests"]) == lists_after_failure
        assert result.payload["watch_stream"]["relists_total"] == {
            "seed": 1, "stream_end": 1,
        }

    def test_inband_410_replay_relists_as_gone(self, stream_world):
        engine = stream_world["make_engine"]()
        engine.tick()
        stream_world["script"].push(fx.watch_error_gone())
        _wait(lambda: not engine.stream_alive(), what="worker exit on 410 replay")
        result, _ = engine.tick()
        assert result.payload["watch_stream"]["relists_total"] == {
            "seed": 1, "gone": 1,
        }

    def test_mid_stream_reset_relists_as_stream_error(self, stream_world):
        engine = stream_world["make_engine"]()
        # Connection 1 resets after one event; connection 2 is live.
        stream_world["script"]._stanzas.insert(
            0,
            {
                "events": [
                    fx.watch_event(
                        "MODIFIED", _tpu_node("ws-0", ready=False),
                        resource_version="150",
                    )
                ],
                "end": "reset",
            },
        )
        engine.tick()
        _wait(lambda: not engine.stream_alive(), what="worker exit on reset")
        result, delta = engine.tick()
        assert "stream_error" in result.payload["watch_stream"]["relists_total"]
        # The event applied before the reset was not lost: either it rode
        # the stream or the relist re-observed the server's (unchanged)
        # truth — the cache and the server agree afterwards.
        assert result.payload["total_nodes"] == 4

    def test_dead_server_raises_like_a_failed_round(self, stream_world):
        # --retry-budget 0: the relist's failure mode, not the retry
        # ladder's patience, is what this test pins.
        engine = stream_world["make_engine"]("--retry-budget", "0")
        engine.tick()
        # Kill the server for real: stop accepting, close the listener, and
        # — as watch() itself does after any failed round — drop the pooled
        # keep-alive client whose sockets may still look alive.
        stream_world["script"].close()
        stream_world["server"].shutdown()
        stream_world["server"].server_close()
        engine.abort_stream()
        checker.reset_client_cache()
        _wait(lambda: not engine.stream_alive(), what="worker death")
        with pytest.raises(Exception):
            engine.tick()

    def test_slow_drip_frames_arrive(self, stream_world):
        engine = stream_world["make_engine"]()
        stream_world["script"]._stanzas.insert(
            0,
            {
                "events": [
                    fx.watch_event(
                        "MODIFIED", _tpu_node("ws-1", ready=False),
                        resource_version="160",
                    ),
                    fx.watch_event(
                        "MODIFIED", _tpu_node("ws-1"), resource_version="161"
                    ),
                ],
                "frame_delay": 0.05,
                "end": "close",
            },
        )
        engine.tick()
        _wait(
            lambda: (engine.stats.as_dict()["events_total"].get("MODIFIED", 0)) >= 2,
            what="dripped frames",
        )


class TestIncrementalSlices:
    """The engine's slice cache must be indistinguishable from a
    from-scratch ``group_slices`` — same SliceInfo payload, same order —
    while provably reusing untouched groups by reference."""

    def _engine_with(self, raw_nodes):
        from tpu_node_checker.detect import extract_node_info

        engine = StreamRoundEngine(
            cli.parse_args(["--watch", "5", "--watch-stream", "--json"])
        )
        engine._infos = {
            i.name: i for i in (extract_node_info(n) for n in raw_nodes)
        }
        engine._accel_names = sorted(engine._infos)
        return engine

    def _full(self, engine):
        from tpu_node_checker.detect import group_slices

        return group_slices([engine._infos[n] for n in engine._accel_names])

    def test_flip_remove_and_label_move_match_full_rebuild(self):
        raw = [
            n for n in fx.big_mixed_cluster()
            if "google.com/tpu" in (n["status"]["allocatable"] or {})
        ][:192]  # three 64-host pools
        engine = self._engine_with(raw)
        first = engine._slices_incremental(frozenset(engine._infos))
        assert [s.to_dict() for s in first] == [
            s.to_dict() for s in self._full(engine)
        ]
        dicts_before = dict(engine._slice_dicts)
        engine._slice_payload(first)  # populate the payload cache

        from tpu_node_checker.detect import extract_node_info

        # Readiness flips inside ONE pool.
        changed = set()
        for n in raw[10:15]:
            for cond in n["status"]["conditions"]:
                if cond["type"] == "Ready":
                    cond["status"] = "False"
            info = extract_node_info(n)
            engine._infos[info.name] = info
            changed.add(info.name)
        inc = engine._slices_incremental(frozenset(changed))
        full = self._full(engine)
        assert engine._slice_payload(inc) == [s.to_dict() for s in full]
        # Untouched groups kept their SliceInfo objects (and therefore
        # their payload dicts) by reference.
        from tpu_node_checker.detect import slice_group_key

        touched = {slice_group_key(engine._infos[n]) for n in changed}
        for key, d in dicts_before.items():
            if key not in touched:
                assert engine._slice_dicts[key] is d

        # A node vanishes entirely.
        victim = raw[100]["metadata"]["name"]
        del engine._infos[victim]
        engine._accel_names = sorted(engine._infos)
        inc = engine._slices_incremental(frozenset({victim}))
        assert engine._slice_payload(inc) == [
            s.to_dict() for s in self._full(engine)
        ]

        # A label move migrates a node between groups (old AND new group
        # rebuilt).
        mover = raw[150]
        mover["metadata"]["labels"]["cloud.google.com/gke-nodepool"] = (
            raw[0]["metadata"]["labels"]["cloud.google.com/gke-nodepool"]
        )
        info = extract_node_info(mover)
        engine._infos[info.name] = info
        inc = engine._slices_incremental(frozenset({info.name}))
        assert engine._slice_payload(inc) == [
            s.to_dict() for s in self._full(engine)
        ]


class TestEvidenceSemantics:
    def test_silent_ticks_bank_nothing_toward_cordon(self, stream_world, tmp_path):
        engine = stream_world["make_engine"](
            "--history", str(tmp_path / "h.jsonl"), "--cordon-after", "2"
        )
        engine.tick()
        # One bad observation: SUSPECT streak 1.
        stream_world["script"].push(
            fx.watch_event(
                "MODIFIED", _tpu_node("ws-0", ready=False), resource_version="400"
            )
        )
        _wait(lambda: engine.cache.pending() >= 1)
        result, _ = engine.tick()
        sick = next(n for n in result.payload["nodes"] if n["name"] == "ws-0")
        assert sick["health"]["state"] == "SUSPECT"
        assert sick["health"]["streak"] == 1
        # Silent ticks: no new evidence — the streak must NOT advance to
        # FAILED the way two poll-mode rounds over a still-bad node would.
        for _ in range(3):
            result, delta = engine.tick()
            assert delta == frozenset()
        sick = next(n for n in result.payload["nodes"] if n["name"] == "ws-0")
        assert sick["health"]["state"] == "SUSPECT"
        assert sick["health"]["streak"] == 1
        # A second OBSERVED bad round crosses the threshold.
        bad = _tpu_node("ws-0", ready=False)
        bad["status"]["conditions"][1]["reason"] = "KubeletNotReady"
        stream_world["script"].push(
            fx.watch_event("MODIFIED", bad, resource_version="401")
        )
        _wait(lambda: engine.cache.pending() >= 1)
        result, _ = engine.tick()
        sick = next(n for n in result.payload["nodes"] if n["name"] == "ws-0")
        assert sick["health"]["state"] == "FAILED"

    def test_steady_tick_reports_no_stale_transitions(self, stream_world, tmp_path):
        engine = stream_world["make_engine"]("--history", str(tmp_path / "h.jsonl"))
        engine.tick()
        stream_world["script"].push(
            fx.watch_event(
                "MODIFIED", _tpu_node("ws-0", ready=False), resource_version="500"
            )
        )
        _wait(lambda: engine.cache.pending() >= 1)
        result, _ = engine.tick()
        assert any(
            t["to"] == "FAILED" for t in result.payload["history"]["transitions"]
        )
        # The next (silent) tick must not repeat the transition — Slack
        # would otherwise re-page on every quiet interval.
        result, delta = engine.tick()
        assert delta == frozenset()
        assert result.payload["history"]["transitions"] == []


class TestWatchLoopIntegration:
    def test_watch_loop_runs_stream_ticks_and_publishes(
        self, stream_world, monkeypatch, capsys
    ):
        import http.client

        ticks = []

        def fake_wait(stop, seconds):
            ticks.append(seconds)
            if len(ticks) == 2:
                # Between rounds 2 and 3: a node sickens.
                stream_world["script"].push(
                    fx.watch_event(
                        "MODIFIED", _tpu_node("ws-1", ready=False),
                        resource_version="600",
                    )
                )
            return len(ticks) >= 4  # stop after 4 rounds

        holder = {}
        from tpu_node_checker.server import app as server_app

        orig_init = server_app.FleetStateServer.__init__

        def spy_init(self, *a, **kw):
            orig_init(self, *a, **kw)
            holder["server"] = self

        monkeypatch.setattr(server_app.FleetStateServer, "__init__", spy_init)
        monkeypatch.setattr(checker, "_wait_for_next_round", fake_wait)
        args = cli.parse_args(
            ["--kubeconfig", stream_world["kubeconfig"], "--watch", "5",
             "--watch-stream", "--serve", "0", "--json"]
        )
        # Deterministic delivery: wait for the event between rounds by
        # polling the engine the loop built — patch tick to block until the
        # pushed event landed.
        orig_tick = StreamRoundEngine.tick

        def synced_tick(self, tracer=None):
            if len(ticks) >= 2:
                deadline = time.perf_counter() + 5.0
                while time.perf_counter() < deadline and self.cache.pending() == 0:
                    time.sleep(0.01)  # tnc: allow-test-wall-clock(bounded poll for a REAL watch socket to deliver the pushed frame before the next loop round)
            return orig_tick(self, tracer=tracer)

        monkeypatch.setattr(StreamRoundEngine, "tick", synced_tick)
        rc = checker.watch(args)
        assert rc == 143
        server = holder["server"]
        snap = server._snap
        assert snap is not None
        # Rounds 1 (seed) and 3 (the sickening) published; steady rounds
        # did not — the served round is 2, not 4.
        assert snap.seq == 2
        sick = snap.node_docs["ws-1"]
        assert sick["ready"] is False
        out = capsys.readouterr()
        assert "Watch-stream mode" in out.err


class TestCliValidation:
    def test_watch_stream_requires_watch(self, capsys):
        with pytest.raises(SystemExit):
            cli.parse_args(["--watch-stream"])
        assert "--watch-stream requires --watch" in capsys.readouterr().err

    def test_no_watch_stream_overrides(self):
        args = cli.parse_args(["--watch", "5", "--watch-stream", "--no-watch-stream"])
        assert args.watch_stream is False

    @pytest.mark.parametrize(
        "extra",
        [
            ["--probe"],
            ["--probe-results", "/tmp/x"],
            ["--node-events"],
            ["--nodes-json", "/tmp/x.json"],
        ],
    )
    def test_rejected_companions(self, extra, capsys):
        with pytest.raises(SystemExit):
            cli.parse_args(["--watch", "5", "--watch-stream", *extra])
        err = capsys.readouterr().err
        assert "--watch-stream" in err
