"""Chaos-hook parity for every fabric probe (VERDICT r01 item #5).

Every "this probe catches X" docstring claim gets a test that injects X via
the probe's chaos hook and asserts the fault is (a) detected and (b)
correctly *named* — the leg, link, stage, or expert the injection targeted,
and only that one.  Real CPU "ICI" cannot be corrupted, so the hooks perturb
the on-device dataflow at the exact point the simulated fault would live.

Runs on conftest's virtual 8-device CPU mesh.
"""

import pytest

from tpu_node_checker.parallel import (
    collective_probe,
    moe_probe,
    pipeline_probe,
    ring_probe,
)

N = 8  # conftest forces 8 virtual devices


class TestCollectiveLegInjection:
    @pytest.mark.parametrize("leg", ["psum", "all_gather", "reduce_scatter"])
    def test_corrupted_leg_flips_its_flag_only(self, leg):
        r = collective_probe(payload=16, timed_iters=1, inject_fault_leg=leg)
        assert not r.ok
        flags = {
            "psum": "psum_ok",
            "all_gather": "all_gather_ok",
            "reduce_scatter": "reduce_scatter_ok",
        }
        for name, flag in flags.items():
            assert r.details[flag] is (name != leg), (leg, r.details)
        assert f"{leg} ok=False" in r.error

    def test_unknown_leg_fails_loudly(self):
        r = collective_probe(payload=16, inject_fault_leg="all_to_all")
        assert not r.ok
        assert "not one of" in r.error

    def test_no_injection_still_healthy(self):
        r = collective_probe(payload=16, timed_iters=1)
        assert r.ok, r.error


class TestRingLinkInjection:
    @pytest.mark.parametrize("link", [0, 3, N - 1])
    def test_corrupted_link_is_named_by_single_hop_diagnostic(self, link):
        r = ring_probe(payload=16, inject_fault_link=link)
        assert not r.ok
        expected = f"{link}->{(link + 1) % N}"
        assert r.details["bad_links"] == [expected], r.details
        assert expected in r.error
        # ...and ONLY that link.
        assert len(r.details["bad_links"]) == 1

    def test_sum_preserving_swap_is_detected_and_localized(self):
        # The fault class position-varying payloads exist for: a link that
        # REORDERS elements (sum unchanged) must still be caught and named —
        # a constant payload would grade this healthy.
        r = ring_probe(payload=16, inject_fault_link=2, inject_fault_swap=True)
        assert not r.ok
        assert r.details["bad_links"] == ["2->3"], r.details

    def test_swap_hook_validated(self):
        r = ring_probe(payload=16, inject_fault_swap=True)
        assert not r.ok
        assert "requires inject_fault_link" in r.error
        r = ring_probe(payload=1, inject_fault_link=0, inject_fault_swap=True)
        assert not r.ok
        assert "payload >= 2" in r.error

    def test_out_of_range_link_fails_loudly(self):
        r = ring_probe(payload=16, inject_fault_link=N)
        assert not r.ok
        assert "out of range" in r.error

    def test_no_injection_still_healthy(self):
        r = ring_probe(payload=16)
        assert r.ok, r.error
        assert "bad_links" not in (r.details or {})


class TestPipelineStageInjection:
    @pytest.mark.parametrize("stage", [0, 2, N - 1])
    def test_corrupted_stage_is_first_bad_checksum(self, stage):
        r = pipeline_probe(inject_fault_stage=stage)
        assert not r.ok
        assert r.details["first_bad_stage"] == stage, r.details
        assert f"stage {stage}" in r.error
        assert len(r.details["stage_checksums"]) == N

    def test_out_of_range_stage_fails_loudly(self):
        r = pipeline_probe(inject_fault_stage=N)
        assert not r.ok
        assert "out of range" in r.error

    def test_no_injection_still_healthy(self):
        r = pipeline_probe()
        assert r.ok, r.error
        assert r.details is None


class TestMoeExpertInjection:
    @pytest.mark.parametrize("expert", [0, 5, N - 1])
    def test_mangled_token_attributes_to_its_expert_only(self, expert):
        r = moe_probe(inject_fault_expert=expert)
        assert not r.ok
        assert r.details["bad_experts"] == [expert], r.details
        assert f"[{expert}]" in r.error

    def test_out_of_range_expert_fails_loudly(self):
        r = moe_probe(inject_fault_expert=N)
        assert not r.ok
        assert "out of range" in r.error

    def test_no_injection_still_healthy(self):
        r = moe_probe()
        assert r.ok, r.error
        assert r.details is None
