"""The declared dependency surface IS the importable surface.

Round-2 verdict finding #1: ``models/burnin.py`` imported optax, which was
declared nowhere — a fresh ``pip install '.[probe]' -c constraints.txt``
could not import the workload probe, even though the dev image (where optax
rides in with flax) passed the whole suite.  The reference prevents exactly
this drift with a complete lockfile (``/root/reference/uv.lock:104-105``
pins kubernetes' full transitive tree).

These tests are the hermetic equivalent of a clean-venv install proof
(CI additionally builds a real fresh venv — ``.github/workflows/ci.yml``
``fresh-install`` job):

* every ``import`` statement anywhere in the package (module level or
  function level) must resolve to the stdlib, the package itself, or a
  dependency declared in ``pyproject.toml``;
* every declared third-party dependency must be pinned in
  ``constraints.txt``;
* every module in the package must actually import.
"""

from __future__ import annotations

import ast
import importlib
import pkgutil
import sys
from pathlib import Path

import tpu_node_checker

REPO = Path(__file__).resolve().parent.parent
# Scan the package actually being imported (source tree locally, installed
# wheel in CI's fresh-install job, where the checkout's package dir is
# deleted) — never a path that can silently not exist.
PKG = Path(tpu_node_checker.__file__).resolve().parent
assert PKG.is_dir(), PKG

# pyproject [project].dependencies + [project.optional-dependencies].probe/test,
# by import name.  Extending this set means extending pyproject AND
# constraints.txt — that is the point.
DECLARED = {
    "requests",  # runtime
    "yaml",  # runtime (PyYAML)
    "jax",  # probe extra
    "jaxlib",  # probe extra (jax transitive, but an explicit jax API surface)
    "numpy",  # probe extra
    "pytest",  # test extra
}

# tests/ may additionally import anything in the test extra.  Round-4 verdict
# weak #1: tests imported hypothesis while the test extra declared only
# pytest, so CI's pinned clean install hit a collection ImportError — the
# package guard above never saw it because it scans only the package.
TEST_DECLARED = DECLARED | {
    "hypothesis",  # test extra
}


def _top_level_imports(path: Path) -> set[str]:
    tree = ast.parse(path.read_text(), filename=str(path))
    names: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            names.update(alias.name.split(".")[0] for alias in node.names)
        elif isinstance(node, ast.ImportFrom):
            if node.level:  # relative import — package-internal by construction
                continue
            if node.module:
                names.add(node.module.split(".")[0])
    return names


def _undeclared_imports(
    root: Path, internal: set[str], declared: set[str]
) -> dict[str, set[str]]:
    """Map undeclared import name → files importing it, under ``root``."""
    undeclared: dict[str, set[str]] = {}
    for path in sorted(root.rglob("*.py")):
        for name in _top_level_imports(path):
            if name in sys.stdlib_module_names or name == "__future__":
                continue
            if name in internal or name in declared:
                continue
            undeclared.setdefault(name, set()).add(str(path))
    return undeclared


def test_every_import_is_declared_or_stdlib():
    undeclared = _undeclared_imports(PKG, {"tpu_node_checker"}, DECLARED)
    assert not undeclared, (
        "imports with no declared dependency (add to pyproject + "
        f"constraints.txt + DECLARED, or drop the import): {undeclared}"
    )


def test_every_test_import_is_declared_or_stdlib():
    """tests/ imports resolve from the declared ``test`` extra, too.

    Same scan as the package guard, pointed at the suite itself, so a
    test-only dependency (hypothesis) can never again be satisfied by the
    dev image while absent from ``pip install '.[probe,test]'``.
    """
    undeclared = _undeclared_imports(
        Path(__file__).resolve().parent,
        {"tpu_node_checker", "tests", "conftest"},
        TEST_DECLARED,
    )
    assert not undeclared, (
        "test imports with no declared dependency (add to the test extra in "
        "pyproject + constraints.txt + TEST_DECLARED, or drop the import): "
        f"{undeclared}"
    )


def test_declared_deps_are_pinned_in_constraints():
    pins = {
        line.split("==")[0].strip().lower().replace("-", "_")
        for line in (REPO / "constraints.txt").read_text().splitlines()
        if "==" in line and not line.lstrip().startswith("#")
    }
    # import name → pip distribution name where they differ
    dist = {"yaml": "pyyaml"}
    missing = {
        name
        for name in TEST_DECLARED  # superset: runtime + probe + test extras
        if dist.get(name, name).lower().replace("-", "_") not in pins
    }
    assert not missing, f"declared deps without an == pin in constraints.txt: {missing}"


def test_every_module_imports():
    import tpu_node_checker

    failures = {}
    for mod in pkgutil.walk_packages(tpu_node_checker.__path__, "tpu_node_checker."):
        try:
            importlib.import_module(mod.name)
        except Exception as exc:  # noqa: BLE001 — collect, report all at once
            failures[mod.name] = f"{type(exc).__name__}: {exc}"
    assert not failures, f"modules that fail to import: {failures}"
