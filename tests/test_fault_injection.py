"""Fault injection: misbehaving API server, probe child, and webhook.

The reference has graded failure *detection* but no fault *injection*
(SURVEY §5.3).  This harness injects failures at both network boundaries
(k8s API, Slack webhook — check-gpu-node.py:217 and :73 analogs) and at the
probe subprocess, and asserts the graded contract holds: transport/API
failures land on exit 1 with a machine-readable error in ``--json`` mode,
probe misbehavior degrades to a structured probe failure, and Slack delivery
failure never changes the exit code.
"""

import json

import pytest

from tests import fixtures as fx
from tpu_node_checker import checker, cli
from tpu_node_checker.probe import run_local_probe
from tpu_node_checker.utils import retry as retry_mod


@pytest.fixture(autouse=True)
def _sleepless_retries(monkeypatch):
    """The graded retry layer is ON by default now; its backoff sleeps must
    not slow this suite down.  The module seam makes every policy's sleeps
    free while keeping request/attempt behavior identical."""
    monkeypatch.setattr(retry_mod, "_sleep", lambda s: None)


class FaultyApiServer:
    """HTTP server with a programmable failure mode per instance — a thin
    wrapper over the scripted fault schedules in tests/fixtures.py (the
    single-shot legacy modes are just schedules whose every entry is the
    same fault)."""

    MODES = {
        "http_500": "500",
        "garbage_json": "garbage_json",
        "truncated": "mid_body_reset",
        "reset": "reset",
        "slow": "slow:10",
        "ok": "ok",
    }

    def __init__(self, mode, nodes=None):
        self.schedule = fx.FaultSchedule([], then=self.MODES.get(mode, mode))
        self.server = fx.serve_http(
            fx.fault_scheduled_handler(
                fx.gpu_pool(1) if nodes is None else nodes, self.schedule
            )
        )

    @property
    def port(self):
        return self.server.server_address[1]

    def close(self):
        self.server.shutdown()


def kubeconfig_for(tmp_path, port):
    p = tmp_path / "kubeconfig"
    p.write_text(
        f"""
apiVersion: v1
kind: Config
current-context: fault
contexts: [{{name: fault, context: {{cluster: fault, user: fault}}}}]
clusters: [{{name: fault, cluster: {{server: "http://127.0.0.1:{port}"}}}}]
users: [{{name: fault, user: {{token: t}}}}]
"""
    )
    return str(p)


class TestApiServerFaults:
    """Every transport-level fault must land on exit 1 — never a traceback
    escaping, never a wrong healthy/unhealthy verdict."""

    @pytest.mark.parametrize("mode", ["http_500", "garbage_json", "truncated", "reset"])
    def test_fault_exits_1_with_json_error(self, tmp_path, capsys, mode):
        srv = FaultyApiServer(mode)
        try:
            code = cli.main(["--json", "--kubeconfig", kubeconfig_for(tmp_path, srv.port)])
        finally:
            srv.close()
        assert code == 1
        out = json.loads(capsys.readouterr().out)
        assert "error" in out and out["error"]

    @pytest.mark.parametrize("mode", ["http_500", "reset"])
    def test_fault_table_mode_stderr(self, tmp_path, capsys, mode):
        srv = FaultyApiServer(mode)
        try:
            code = cli.main(["--kubeconfig", kubeconfig_for(tmp_path, srv.port)])
        finally:
            srv.close()
        assert code == 1
        captured = capsys.readouterr()
        assert captured.err  # human mode explains on stderr

    def test_slow_server_times_out_to_exit_1(self, tmp_path, capsys, monkeypatch):
        # Client-side timeout (DEFAULT_TIMEOUT_S) shrunk so the test is fast.
        import tpu_node_checker.cluster as cluster

        srv = FaultyApiServer("slow")
        orig = cluster.KubeClient.list_nodes

        def fast_timeout(self, label_selector=None, timeout=0.5):
            return orig(self, label_selector=label_selector, timeout=0.5)

        monkeypatch.setattr(cluster.KubeClient, "list_nodes", fast_timeout)
        try:
            code = cli.main(["--json", "--kubeconfig", kubeconfig_for(tmp_path, srv.port)])
        finally:
            srv.close()
        assert code == 1
        assert "error" in json.loads(capsys.readouterr().out)

    def test_healthy_server_control(self, tmp_path, capsys):
        # The harness itself must not be the reason anything fails.
        srv = FaultyApiServer("ok")
        try:
            code = cli.main(["--json", "--kubeconfig", kubeconfig_for(tmp_path, srv.port)])
        finally:
            srv.close()
        assert code == 0


class TestProbeChildFaults:
    def test_child_emits_garbage_stdout(self):
        # /bin/echo prints the script text (not JSON) and exits 0.
        r = run_local_probe(level="enumerate", timeout_s=10, python="/bin/echo")
        assert not r.ok
        assert "without a report" in r.error

    def test_child_killed_by_signal(self, tmp_path):
        die = tmp_path / "die"
        die.write_text("#!/bin/sh\nkill -9 $$\n")
        die.chmod(0o755)
        r = run_local_probe(level="enumerate", timeout_s=10, python=str(die))
        assert not r.ok
        assert "without a report" in r.error

    def test_child_oom_like_abort(self, tmp_path):
        # Emulates libtpu abort()ing after partial stderr output.
        ab = tmp_path / "abort"
        ab.write_text("#!/bin/sh\necho 'F0000 check failure' >&2\nexit 134\n")
        ab.chmod(0o755)
        r = run_local_probe(level="enumerate", timeout_s=10, python=str(ab))
        assert not r.ok
        assert "134" in r.error or "check failure" in r.error


class TestSlackFaultIsolation:
    """Slack delivery failure must never alter the check's exit code
    (check-gpu-node.py:269-271 contract)."""

    def test_webhook_down_keeps_exit_code(self, capsys):
        srv = FaultyApiServer("reset")  # reused as a dead webhook endpoint
        try:
            args = cli.parse_args(
                ["--slack-webhook", f"http://127.0.0.1:{srv.port}/hook",
                 "--slack-retry-count", "0", "--slack-retry-delay", "0"]
            )
            code = checker.one_shot(args, nodes=fx.tpu_v5e_single_host())
        finally:
            srv.close()
        assert code == 0
        assert "Slack notification failed" in capsys.readouterr().err

    def test_webhook_http_500_keeps_exit_code(self, capsys):
        srv = FaultyApiServer("http_500")
        try:
            args = cli.parse_args(
                ["--slack-webhook", f"http://127.0.0.1:{srv.port}/hook",
                 "--slack-retry-count", "0", "--slack-retry-delay", "0"]
            )
            code = checker.one_shot(args, nodes=fx.gpu_pool(1, ready=False))
        finally:
            srv.close()
        assert code == 3  # the cluster verdict, not the webhook's


class TestGradedRetryRecovery:
    """Acceptance: transient faults recoverable within budget leave the
    verdict and payload matching the fault-free run (retries counted in the
    transport telemetry); an exhausted budget still lands on exit 1 with a
    machine-readable error — the documented contract, unchanged."""

    NODES = fx.tpu_v5p_64_slice()[:8]  # an 8-node run

    def _run(self, tmp_path, capsys, schedule, extra_flags=()):
        srv = fx.serve_http(fx.fault_scheduled_handler(self.NODES, schedule))
        try:
            code = cli.main(
                ["--json", *extra_flags,
                 "--kubeconfig",
                 kubeconfig_for(tmp_path, srv.server_address[1])]
            )
            payload = json.loads(capsys.readouterr().out)
        finally:
            srv.shutdown()
            checker.reset_client_cache()
        return code, payload

    def test_recoverable_faults_same_grade_and_payload_as_fault_free(
        self, tmp_path, capsys
    ):
        code, control = self._run(tmp_path, capsys, fx.FaultSchedule([]))
        assert code == 0
        assert control["api_transport"]["retries"] == 0
        assert "degraded" not in control  # fault-free: no degradation key

        # One reset (first — on a FRESH connection, so it exercises the
        # retry layer rather than the transport's reused-socket redial),
        # one 500, one throttle (Retry-After: 0) — all absorbed within the
        # default budget; the 4th request succeeds.
        faulted_schedule = fx.FaultSchedule(["reset", "500", "429:0"])
        code2, faulted = self._run(tmp_path, capsys, faulted_schedule)
        assert code2 == 0
        assert faulted["api_transport"]["retries"] >= 3
        for key in ("exit_code", "total_nodes", "ready_nodes", "total_chips",
                    "ready_chips", "nodes", "slices"):
            assert faulted[key] == control[key], key
        assert "degraded" not in faulted  # the LIST recovered fully

    def test_exhausted_budget_exits_1_with_machine_readable_error(
        self, tmp_path, capsys, monkeypatch
    ):
        # Fake clock end-to-end: sleeps advance it, the budget reads it.
        clock = {"t": 0.0}
        monkeypatch.setattr(
            retry_mod, "_sleep",
            lambda s: clock.__setitem__("t", clock["t"] + s),
        )
        monkeypatch.setattr(retry_mod, "_monotonic", lambda: clock["t"])
        # Persistent 500s against a budget smaller than the first backoff:
        # the first grant drains it, the second failure finds it dry.
        schedule = fx.FaultSchedule([], then="500")
        code, payload = self._run(
            tmp_path, capsys, schedule, extra_flags=("--retry-budget", "0.001")
        )
        assert code == 1
        assert "error" in payload and "500" in payload["error"]
        # Budget (not the per-call attempt cap) ended the sequence: the
        # server saw exactly two requests, not DEFAULT_MAX_ATTEMPTS.
        assert schedule.served == ["500", "500"]

    def test_retry_budget_zero_disables_retries(self, tmp_path, capsys):
        schedule = fx.FaultSchedule([], then="500")
        code, payload = self._run(
            tmp_path, capsys, schedule, extra_flags=("--retry-budget", "0")
        )
        assert code == 1
        assert "error" in payload
        assert schedule.served == ["500"]  # one shot, the pre-retry contract

    def test_retry_budget_flag_validation(self, capsys):
        with pytest.raises(SystemExit) as e:
            cli.parse_args(["--retry-budget", "-1"])
        assert e.value.code == 2
        assert "--retry-budget" in capsys.readouterr().err
        assert cli.parse_args(["--retry-budget", "0"]).retry_budget == 0.0


class TestPartialDegradation:
    """Transient failures in NON-essential phases (events fetch, cordon /
    uncordon sweeps) mark the round ``degraded: true`` with per-phase error
    detail — the verdict and exit code stand; only a failed initial node
    LIST keeps the exit-1 contract."""

    def test_events_fetch_failure_degrades_round_not_exit_code(
        self, monkeypatch, capsys
    ):
        from tpu_node_checker.cluster import ClusterAPIError

        class FlakyEventsClient:
            def list_node_events(self, name, timeout=None, limit=100):
                raise ClusterAPIError("HTTP 503: events backend down", 503)

        monkeypatch.setattr(
            checker, "_resolve_client", lambda args, client: FlakyEventsClient()
        )
        args = cli.parse_args(["--node-events", "--json"])
        result = checker.run_check(args, nodes=fx.tpu_v5p_64_slice(not_ready=2))
        assert result.exit_code == 0  # 14 Ready hosts: the verdict stands
        assert result.payload["degraded"] is True
        events_errors = result.payload["degradation"]["events"]
        assert len(events_errors) == 2
        assert all("503" in e for e in events_errors)
        capsys.readouterr()

    def test_no_cluster_client_for_events_degrades(self, monkeypatch, capsys):
        def no_client(args, client):
            raise RuntimeError("no kubeconfig anywhere")

        monkeypatch.setattr(checker, "_resolve_client", no_client)
        args = cli.parse_args(["--node-events", "--json"])
        result = checker.run_check(args, nodes=fx.tpu_v5p_64_slice(not_ready=1))
        assert result.exit_code == 0
        assert result.payload["degraded"] is True
        assert "no cluster client" in result.payload["degradation"]["events"][0]
        capsys.readouterr()

    def test_cordon_patch_failure_degrades_round(
        self, tmp_path, monkeypatch, capsys
    ):
        class DeadPatchClient:
            def cordon_node(self, name, timeout=None):
                raise ConnectionResetError("PATCH socket died")

        monkeypatch.setattr(
            checker, "_resolve_client", lambda args, client: DeadPatchClient()
        )
        reports = tmp_path / "reports"
        reports.mkdir()
        (reports / "gke-tpu-v5p-0.json").write_text(
            json.dumps({"ok": False, "level": "compute",
                        "hostname": "gke-tpu-v5p-0", "error": "chips dead"})
        )
        args = cli.parse_args(
            ["--probe-results", str(reports), "--cordon-failed", "--json"]
        )
        result = checker.run_check(args, nodes=fx.tpu_v5p_64_slice())
        assert result.exit_code == 0  # 15 healthy hosts: verdict stands
        assert result.payload["degraded"] is True
        assert "gke-tpu-v5p-0" in result.payload["degradation"]["cordon"][0]
        assert result.payload["cordon"]["failed"]  # detail preserved too
        capsys.readouterr()

    def test_healthy_round_has_no_degradation_keys(self, capsys):
        result = checker.run_check(
            cli.parse_args(["--json"]), nodes=fx.tpu_v5p_64_slice()
        )
        assert "degraded" not in result.payload
        assert "degradation" not in result.payload
        capsys.readouterr()

    def test_degraded_round_flagged_in_state_log(
        self, tmp_path, monkeypatch, capsys
    ):
        def no_client(args, client):
            raise RuntimeError("unreachable")

        monkeypatch.setattr(checker, "_resolve_client", no_client)
        log = tmp_path / "trend.jsonl"
        args = cli.parse_args(
            ["--node-events", "--json", "--log-jsonl", str(log)]
        )
        code = checker.one_shot(args, nodes=fx.tpu_v5p_64_slice(not_ready=1))
        assert code == 0
        (entry,) = [json.loads(x) for x in log.read_text().splitlines()]
        assert entry["degraded"] is True
        assert entry["exit_code"] == 0
        capsys.readouterr()
