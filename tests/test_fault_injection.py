"""Fault injection: misbehaving API server, probe child, and webhook.

The reference has graded failure *detection* but no fault *injection*
(SURVEY §5.3).  This harness injects failures at both network boundaries
(k8s API, Slack webhook — check-gpu-node.py:217 and :73 analogs) and at the
probe subprocess, and asserts the graded contract holds: transport/API
failures land on exit 1 with a machine-readable error in ``--json`` mode,
probe misbehavior degrades to a structured probe failure, and Slack delivery
failure never changes the exit code.
"""

import json
import socket
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from tests import fixtures as fx
from tpu_node_checker import checker, cli
from tpu_node_checker.probe import run_local_probe


class FaultyApiServer:
    """HTTP server with a programmable failure mode per instance."""

    def __init__(self, mode, payload=None):
        self.mode = mode
        self.payload = payload or json.dumps(fx.node_list(fx.gpu_pool(1))).encode()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                if outer.mode == "http_500":
                    body = b'{"kind":"Status","message":"etcdserver: timeout"}'
                    self.send_response(500)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif outer.mode == "garbage_json":
                    body = b"<html>proxy error</html>"
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif outer.mode == "truncated":
                    # Advertise more bytes than are sent, then slam the socket.
                    self.send_response(200)
                    self.send_header("Content-Length", str(len(outer.payload) + 999))
                    self.end_headers()
                    self.wfile.write(outer.payload[: len(outer.payload) // 2])
                    self.wfile.flush()
                    self.connection.close()
                elif outer.mode == "reset":
                    # RST instead of a response: connection reset by peer.
                    self.connection.setsockopt(
                        socket.SOL_SOCKET, socket.SO_LINGER,
                        b"\x01\x00\x00\x00\x00\x00\x00\x00",
                    )
                    self.connection.close()
                elif outer.mode == "slow":
                    # Trickle one byte, then stall past the client timeout.
                    self.send_response(200)
                    self.send_header("Content-Length", str(len(outer.payload)))
                    self.end_headers()
                    self.wfile.write(outer.payload[:1])
                    self.wfile.flush()
                    import time as _t

                    _t.sleep(10)
                else:  # "ok"
                    self.send_response(200)
                    self.send_header("Content-Length", str(len(outer.payload)))
                    self.end_headers()
                    self.wfile.write(outer.payload)

            def log_message(self, *args):
                pass

        self.server = HTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=self.server.serve_forever, daemon=True).start()

    @property
    def port(self):
        return self.server.server_address[1]

    def close(self):
        self.server.shutdown()


def kubeconfig_for(tmp_path, port):
    p = tmp_path / "kubeconfig"
    p.write_text(
        f"""
apiVersion: v1
kind: Config
current-context: fault
contexts: [{{name: fault, context: {{cluster: fault, user: fault}}}}]
clusters: [{{name: fault, cluster: {{server: "http://127.0.0.1:{port}"}}}}]
users: [{{name: fault, user: {{token: t}}}}]
"""
    )
    return str(p)


class TestApiServerFaults:
    """Every transport-level fault must land on exit 1 — never a traceback
    escaping, never a wrong healthy/unhealthy verdict."""

    @pytest.mark.parametrize("mode", ["http_500", "garbage_json", "truncated", "reset"])
    def test_fault_exits_1_with_json_error(self, tmp_path, capsys, mode):
        srv = FaultyApiServer(mode)
        try:
            code = cli.main(["--json", "--kubeconfig", kubeconfig_for(tmp_path, srv.port)])
        finally:
            srv.close()
        assert code == 1
        out = json.loads(capsys.readouterr().out)
        assert "error" in out and out["error"]

    @pytest.mark.parametrize("mode", ["http_500", "reset"])
    def test_fault_table_mode_stderr(self, tmp_path, capsys, mode):
        srv = FaultyApiServer(mode)
        try:
            code = cli.main(["--kubeconfig", kubeconfig_for(tmp_path, srv.port)])
        finally:
            srv.close()
        assert code == 1
        captured = capsys.readouterr()
        assert captured.err  # human mode explains on stderr

    def test_slow_server_times_out_to_exit_1(self, tmp_path, capsys, monkeypatch):
        # Client-side timeout (DEFAULT_TIMEOUT_S) shrunk so the test is fast.
        import tpu_node_checker.cluster as cluster

        srv = FaultyApiServer("slow")
        orig = cluster.KubeClient.list_nodes

        def fast_timeout(self, label_selector=None, timeout=0.5):
            return orig(self, label_selector=label_selector, timeout=0.5)

        monkeypatch.setattr(cluster.KubeClient, "list_nodes", fast_timeout)
        try:
            code = cli.main(["--json", "--kubeconfig", kubeconfig_for(tmp_path, srv.port)])
        finally:
            srv.close()
        assert code == 1
        assert "error" in json.loads(capsys.readouterr().out)

    def test_healthy_server_control(self, tmp_path, capsys):
        # The harness itself must not be the reason anything fails.
        srv = FaultyApiServer("ok")
        try:
            code = cli.main(["--json", "--kubeconfig", kubeconfig_for(tmp_path, srv.port)])
        finally:
            srv.close()
        assert code == 0


class TestProbeChildFaults:
    def test_child_emits_garbage_stdout(self):
        # /bin/echo prints the script text (not JSON) and exits 0.
        r = run_local_probe(level="enumerate", timeout_s=10, python="/bin/echo")
        assert not r.ok
        assert "without a report" in r.error

    def test_child_killed_by_signal(self, tmp_path):
        die = tmp_path / "die"
        die.write_text("#!/bin/sh\nkill -9 $$\n")
        die.chmod(0o755)
        r = run_local_probe(level="enumerate", timeout_s=10, python=str(die))
        assert not r.ok
        assert "without a report" in r.error

    def test_child_oom_like_abort(self, tmp_path):
        # Emulates libtpu abort()ing after partial stderr output.
        ab = tmp_path / "abort"
        ab.write_text("#!/bin/sh\necho 'F0000 check failure' >&2\nexit 134\n")
        ab.chmod(0o755)
        r = run_local_probe(level="enumerate", timeout_s=10, python=str(ab))
        assert not r.ok
        assert "134" in r.error or "check failure" in r.error


class TestSlackFaultIsolation:
    """Slack delivery failure must never alter the check's exit code
    (check-gpu-node.py:269-271 contract)."""

    def test_webhook_down_keeps_exit_code(self, capsys):
        srv = FaultyApiServer("reset")  # reused as a dead webhook endpoint
        try:
            args = cli.parse_args(
                ["--slack-webhook", f"http://127.0.0.1:{srv.port}/hook",
                 "--slack-retry-count", "0", "--slack-retry-delay", "0"]
            )
            code = checker.one_shot(args, nodes=fx.tpu_v5e_single_host())
        finally:
            srv.close()
        assert code == 0
        assert "Slack notification failed" in capsys.readouterr().err

    def test_webhook_http_500_keeps_exit_code(self, capsys):
        srv = FaultyApiServer("http_500")
        try:
            args = cli.parse_args(
                ["--slack-webhook", f"http://127.0.0.1:{srv.port}/hook",
                 "--slack-retry-count", "0", "--slack-retry-delay", "0"]
            )
            code = checker.one_shot(args, nodes=fx.gpu_pool(1, ready=False))
        finally:
            srv.close()
        assert code == 3  # the cluster verdict, not the webhook's
