"""The observability layer: tracer spans, native histograms, the event
log, the debug-rounds endpoints, and the two-tier federation trace.

The contracts under test (DESIGN.md §15):

* **bucket math** — ``le`` is ≤ (a value equal to a bound lands in THAT
  bucket), ``_bucket`` lines are cumulative with ``+Inf`` == ``_count``,
  and merging per-thread recorders at scrape time loses nothing: the
  merged family over N concurrent writers is element-wise identical to
  the same observations recorded serially;
* **nested spans** — parent/child depth and offsets survive into the
  Chrome-trace document; spans recorded from other threads land on their
  own ``tid``; the flat ``PhaseTimer`` surface (``phase``/``as_dict``/
  ``chrome_trace``) is unchanged;
* **ring** — the debug ring holds exactly the last N completed traces
  (newest first), eviction never tears a reader: under a live HTTP hammer
  against ``/api/v1/debug/rounds`` every 200 parses while the writer
  pushes;
* **two tiers, one trace** — a federation round's trace document contains
  the aggregator's fetch/merge/publish spans AND the upstream cluster
  round's spans, each tier's ``trace_id`` present, stitched via the
  ``X-TNC-Trace`` response header.

Wall-clock guard: same policy as tests/test_server.py — nothing here
sleeps for real.
"""

import http.client
import json
import threading
import time

import pytest

from tests import fixtures as fx
from tpu_node_checker import checker, cli
from tpu_node_checker.obs import (
    DEFAULT_LATENCY_BUCKETS_MS,
    EventLog,
    HistogramFamily,
    Observability,
    TraceRing,
    Tracer,
)
from tpu_node_checker.obs.hist import Histogram, _fmt
from tpu_node_checker.server.app import FleetStateServer
from tpu_node_checker.utils.timing import PhaseTimer

WALL_CLOCK_BUDGET_S = 20.0


@pytest.fixture(autouse=True)
def _wall_clock_guard():
    t0 = time.perf_counter()
    yield
    elapsed = time.perf_counter() - t0
    assert elapsed < WALL_CLOCK_BUDGET_S, (
        f"obs test burned {elapsed:.1f}s of wall-clock — a real sleep or "
        "a wedged thread leaked in"
    )


def _req(port, path, headers=None, method="GET"):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    try:
        conn.request(method, path, headers=headers or {})
        resp = conn.getresponse()
        return resp.status, dict(resp.headers.items()), resp.read()
    finally:
        conn.close()


class _Round:
    def __init__(self, payload, exit_code=0):
        self.payload = payload
        self.exit_code = exit_code


def _round_payload(n=2, trace_id=None, cluster=None):
    payload = {
        "total_nodes": n,
        "ready_nodes": n,
        "total_chips": n * 4,
        "ready_chips": n * 4,
        "nodes": [
            {"name": f"n-{i}", "ready": True, "accelerators": 4}
            for i in range(n)
        ],
        "slices": [],
        "exit_code": 0,
    }
    if trace_id:
        payload["trace_id"] = trace_id
    if cluster:
        payload["cluster"] = cluster
        payload["cluster_source"] = "flag"
    return payload


# ---------------------------------------------------------------------------
# Histogram bucket math
# ---------------------------------------------------------------------------


class TestHistogramBuckets:
    def test_boundary_value_lands_in_its_own_bucket(self):
        # Prometheus le is ≤: an observation equal to a bound belongs to
        # THAT bucket, not the next one.
        h = Histogram((1.0, 5.0))
        h.record(1.0)
        assert h.counts == [1, 0, 0]
        h.record(5.0)
        assert h.counts == [1, 1, 0]
        h.record(0.5)
        h.record(1.5)
        h.record(50.0)  # +Inf overflow
        assert h.counts == [2, 2, 1]
        assert h.count == 5
        assert h.total == pytest.approx(1.0 + 5.0 + 0.5 + 1.5 + 50.0)

    def test_bucket_lines_are_cumulative_and_inf_equals_count(self):
        fam = HistogramFamily(
            "tpu_node_checker_test_wait_ms", "test", (1.0, 5.0, 10.0)
        )
        for value in (0.5, 0.5, 3.0, 7.0, 100.0):
            fam.record(value)
        lines = fam.prometheus_lines()
        assert f"# TYPE tpu_node_checker_test_wait_ms histogram" in lines
        samples = {
            line.rsplit(" ", 1)[0]: float(line.rsplit(" ", 1)[1])
            for line in lines if not line.startswith("#")
        }
        assert samples['tpu_node_checker_test_wait_ms_bucket{le="1"}'] == 2.0
        assert samples['tpu_node_checker_test_wait_ms_bucket{le="5"}'] == 3.0
        assert samples['tpu_node_checker_test_wait_ms_bucket{le="10"}'] == 4.0
        assert samples['tpu_node_checker_test_wait_ms_bucket{le="+Inf"}'] == 5.0
        assert samples["tpu_node_checker_test_wait_ms_count"] == 5.0
        assert samples["tpu_node_checker_test_wait_ms_sum"] == pytest.approx(
            111.0
        )

    def test_le_labels_render_trailing_zero_free(self):
        # Identical bounds must always render identical le values, or a
        # scrape's series names would split across restarts.
        assert [_fmt(b) for b in (0.1, 0.25, 1.0, 5.0, 1000.0, 2500.0)] == [
            "0.1", "0.25", "1", "5", "1000", "2500"
        ]

    def test_labeled_family_renders_per_label_series(self):
        fam = HistogramFamily(
            "tpu_node_checker_test_phase_ms", "test", (1.0,), label="phase"
        )
        fam.record(0.5, "fold")
        fam.record(2.0, "grade")
        lines = [l for l in fam.prometheus_lines() if not l.startswith("#")]
        assert any('le="1",phase="fold"' in l and l.endswith(" 1.0")
                   for l in lines)
        assert any('le="1",phase="grade"' in l and l.endswith(" 0.0")
                   for l in lines)
        merged = fam.merged()
        assert set(merged) == {"fold", "grade"}

    def test_default_ladder_covers_the_project_budgets(self):
        # The asserted perf budgets (serve p99 < 5ms, steady round < 10ms)
        # need a bound AT the budget for histogram_quantile to answer
        # "did we blow it" without interpolation across it.
        assert 5.0 in DEFAULT_LATENCY_BUCKETS_MS
        assert 10.0 in DEFAULT_LATENCY_BUCKETS_MS
        assert tuple(sorted(DEFAULT_LATENCY_BUCKETS_MS)) == (
            DEFAULT_LATENCY_BUCKETS_MS
        )


class TestHistogramConcurrency:
    def test_multi_worker_record_merge_identity(self):
        # N threads hammer the same labeled family; the merged result must
        # be element-wise identical to the same observations recorded
        # serially — per-thread recorders lose nothing at merge time.
        fam = HistogramFamily(
            "tpu_node_checker_test_conc_ms", "test", (1.0, 5.0, 25.0),
            label="route",
        )
        serial = HistogramFamily(
            "tpu_node_checker_test_serial_ms", "test", (1.0, 5.0, 25.0),
            label="route",
        )
        values = [0.2, 1.0, 3.0, 5.0, 7.0, 30.0, 0.9, 25.0]
        workers = 8
        rounds = 50
        start = threading.Barrier(workers)

        def worker(slot):
            start.wait(timeout=10)
            label = f"r{slot % 2}"
            for _ in range(rounds):
                for value in values:
                    fam.record(value, label)

        threads = [
            threading.Thread(target=worker, args=(slot,),
                             name=f"tnc-test-hist-{slot}", daemon=True)
            for slot in range(workers)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        assert not any(t.is_alive() for t in threads)
        for slot in range(workers):
            label = f"r{slot % 2}"
            for _ in range(rounds):
                for value in values:
                    serial.record(value, label)
        merged = fam.merged()
        expected = serial.merged()
        assert set(merged) == set(expected) == {"r0", "r1"}
        for label in merged:
            counts, total, count = merged[label]
            e_counts, e_total, e_count = expected[label]
            assert counts == e_counts
            assert count == e_count
            assert total == pytest.approx(e_total)

    def test_thread_churn_reuses_recorders_and_keeps_counts(self):
        # Both major recording surfaces run on SHORT-LIVED threads
        # (thread-per-connection handlers, per-round federation fetchers):
        # a dead thread's recorder must return to the family for re-lease —
        # bounded recorder count under churn — while its accumulated
        # samples keep scraping (counts are cumulative, never dropped).
        import gc

        fam = HistogramFamily(
            "tpu_node_checker_test_churn_ms", "test", (1.0, 5.0),
            label="route",
        )
        for generation in range(40):  # sequential short-lived threads
            t = threading.Thread(
                target=lambda: fam.record(0.5, "r"),
                name=f"tnc-test-churn-{generation}", daemon=True,
            )
            t.start()
            t.join(timeout=10)
            assert not t.is_alive()
        gc.collect()  # finalizer timing must not be why this passes
        counts, total, count = fam.merged()["r"]
        assert count == 40 and counts[0] == 40
        assert total == pytest.approx(40 * 0.5)
        # 40 dead threads leased far fewer than 40 recorders (sequential
        # churn re-leases the same returned one, give or take finalizer
        # lag at the margin).
        assert len(fam._recorders) <= 3, len(fam._recorders)

    def test_dedicated_recorder_feeds_the_same_merge(self):
        fam = HistogramFamily(
            "tpu_node_checker_test_dedicated_ms", "test", (1.0,),
            label="phase",
        )
        rec = fam.recorder("fold")
        rec.record(0.5)
        fam.record(2.0, "fold")  # thread-local path, same label
        counts, total, count = fam.merged()["fold"]
        assert counts == [1, 1] and count == 2
        assert total == pytest.approx(2.5)


# ---------------------------------------------------------------------------
# Tracer: nested spans, threads, compat surface
# ---------------------------------------------------------------------------


class TestTracerSpans:
    def test_nested_spans_record_depth_and_offsets(self):
        tracer = Tracer(round_seq=7)
        with tracer.span("grade", changed=3):
            with tracer.span("detect"):
                pass
            with tracer.span("fsm"):
                pass
        # Children complete before the parent; depth reflects nesting.
        names = [(s[0], s[3]) for s in tracer.spans]
        assert names == [("detect", 1), ("fsm", 1), ("grade", 0)]
        by_name = {s[0]: s for s in tracer.spans}
        _, g_start, g_dur, _, _, g_args = by_name["grade"]
        for child in ("detect", "fsm"):
            _, c_start, c_dur, _, _, _ = by_name[child]
            assert c_start >= g_start
            assert c_start + c_dur <= g_start + g_dur + 0.5
        assert g_args == {"changed": 3}
        # detect and fsm are siblings in execution order.
        assert by_name["detect"][1] <= by_name["fsm"][1]

    def test_chrome_trace_carries_identity_depth_and_total(self):
        tracer = Tracer(round_seq=3, mode="round")
        with tracer.span("fold"):
            pass
        tracer.finish()
        doc = tracer.chrome_trace()
        assert doc["otherData"]["trace_id"] == tracer.trace_id
        assert doc["otherData"]["round_seq"] == 3
        events = doc["traceEvents"]
        meta = next(e for e in events if e["name"] == "trace_id")
        assert meta["args"]["trace_id"] == tracer.trace_id
        fold = next(e for e in events if e["name"] == "fold")
        assert fold["ph"] == "X" and fold["args"]["depth"] == 0
        total = next(e for e in events if e["name"] == "total")
        assert fold["ts"] + fold["dur"] <= total["dur"] * 1.05
        # The document round-trips as JSON bytes (the debug endpoint body).
        assert json.loads(tracer.chrome_trace_bytes())["traceEvents"]

    def test_spans_from_other_threads_get_their_own_tid(self):
        tracer = Tracer()

        def fetcher():
            with tracer.span("fetch", cluster="us-a"):
                pass

        thread = threading.Thread(target=fetcher, name="tnc-test-fetcher",
                                  daemon=True)
        with tracer.span("round"):
            thread.start()
            thread.join(timeout=10)
        assert not thread.is_alive()
        tids = {s[0]: s[4] for s in tracer.spans}
        assert tids["fetch"] != tids["round"]

    def test_phase_timer_compat_surface(self):
        # The original PhaseTimer API: phase()/phases/as_dict()/total_ms().
        timer = PhaseTimer()
        assert isinstance(timer, Tracer)
        assert timer.trace_id  # every timer now mints a trace identity
        with timer.phase("list"):
            pass
        with timer.phase("list"):
            pass  # repeated phases accumulate, as before
        out = timer.as_dict()
        assert set(out) == {"list", "total"}
        assert out["list"] >= 0.0
        assert timer.phases["list"] == pytest.approx(
            sum(s[2] for s in timer.spans)
        )

    def test_finish_freezes_total(self):
        tracer = Tracer()
        tracer.finish()
        frozen = tracer.total_ms()
        with tracer.span("late"):
            pass
        assert tracer.total_ms() == frozen

    def test_error_rides_summary_and_document(self):
        tracer = Tracer()
        tracer.set_error("relist failed: HTTP 503")
        tracer.finish()
        assert tracer.summary()["error"] == "relist failed: HTTP 503"
        assert tracer.chrome_trace()["otherData"]["error"] == (
            "relist failed: HTTP 503"
        )

    def test_attach_subtrace_rebase_and_label(self):
        tracer = Tracer()
        sub_events = [
            {"name": "process_name", "ph": "M", "pid": 1, "tid": 1,
             "args": {"name": "tpu-node-checker"}},
            {"name": "fold", "ph": "X", "pid": 1, "tid": 1,
             "ts": 0.0, "dur": 5.0},
        ]
        tracer.attach_subtrace("cluster:us-a", sub_events, trace_id="abc123")
        tracer.finish()
        events = tracer.chrome_trace()["traceEvents"]
        track = [e for e in events if e.get("pid") == 2]
        labels = [e["args"]["name"] for e in track
                  if e["name"] == "process_name"]
        # The sub-trace's own process_name metadata must NOT override the
        # cluster label.
        assert labels == ["cluster:us-a"]
        fold = next(e for e in track if e["name"] == "fold")
        assert fold["pid"] == 2 and fold["dur"] == 5.0
        assert tracer.summary()["subtraces"] == [
            {"label": "cluster:us-a", "trace_id": "abc123"}
        ]


# ---------------------------------------------------------------------------
# The ring
# ---------------------------------------------------------------------------


class TestTraceRing:
    def _completed(self, seq):
        tracer = Tracer(round_seq=seq)
        tracer.finish()
        return tracer

    def test_eviction_keeps_the_last_n_newest_first(self):
        ring = TraceRing(4)
        tracers = [self._completed(i) for i in range(10)]
        for tracer in tracers:
            ring.push(tracer)
        entries = ring.entries()
        assert [t.round_seq for t in entries] == [9, 8, 7, 6]
        assert ring.find(tracers[9].trace_id) is tracers[9]
        assert ring.find(tracers[0].trace_id) is None  # evicted

    def test_partial_ring_returns_only_pushed(self):
        ring = TraceRing(8)
        ring.push(self._completed(1))
        assert [t.round_seq for t in ring.entries()] == [1]

    def test_concurrent_readers_never_see_an_unfinished_trace(self):
        ring = TraceRing(4)
        stop = threading.Event()
        errors = []

        def reader():
            while not stop.is_set():
                for entry in ring.entries():
                    if entry._total_ms is None:
                        errors.append("reader saw an unfinished tracer")
                        return

        threads = [
            threading.Thread(target=reader, name=f"tnc-test-ring-{i}",
                             daemon=True)
            for i in range(4)
        ]
        for t in threads:
            t.start()
        for seq in range(500):
            ring.push(self._completed(seq))
        stop.set()
        for t in threads:
            t.join(timeout=10)
        assert not any(t.is_alive() for t in threads)
        assert errors == []


# ---------------------------------------------------------------------------
# Event log
# ---------------------------------------------------------------------------


class TestEventLog:
    def test_emit_writes_stderr_and_file(self, tmp_path, capsys):
        path = tmp_path / "events.jsonl"
        log = EventLog(str(path), cluster="us-a")
        entry = log.emit("breaker-opened", trace_id="t1",
                         consecutive_failures=3, absent=None)
        assert entry["cluster"] == "us-a" and entry["trace_id"] == "t1"
        assert "absent" not in entry  # None fields never serialize
        line = capsys.readouterr().err.strip()
        assert json.loads(line) == entry
        events, skipped = EventLog.load(str(path))
        assert skipped == 0 and events == [entry]

    def test_load_is_torn_line_tolerant(self, tmp_path, capsys):
        path = tmp_path / "events.jsonl"
        log = EventLog(str(path))
        log.emit("fsm-transition", node="n-1")
        log.emit("fsm-transition", node="n-2")
        with open(path, "a") as f:
            f.write('{"event": "torn')  # crash mid-write
        events, skipped = EventLog.load(str(path))
        assert [e["node"] for e in events] == ["n-1", "n-2"]
        assert skipped == 1

    def test_unwritable_path_degrades_to_stderr_only(self, tmp_path, capsys):
        log = EventLog(str(tmp_path / "no" / "dir" / "e.jsonl"))
        entry = log.emit("shard-degraded", shard="us-a")
        err = capsys.readouterr().err
        assert json.dumps(entry, ensure_ascii=False) in err
        assert "unwritable" in err
        log.emit("shard-degraded", shard="eu-b")
        # One outage note, not one per event.
        assert "unwritable" not in capsys.readouterr().err


# ---------------------------------------------------------------------------
# Debug endpoints + identity headers
# ---------------------------------------------------------------------------


class TestDebugEndpoints:
    def _server_with_round(self, obs):
        srv = FleetStateServer(0, host="127.0.0.1", obs=obs)
        tracer = obs.tracer(1)
        with tracer.span("fold"):
            pass
        with tracer.span("publish"):
            srv.publish(
                _Round(_round_payload(trace_id=tracer.trace_id)),
                tracer=tracer,
            )
        obs.complete(tracer)
        return srv, tracer

    def test_rounds_list_and_detail(self):
        obs = Observability()
        srv, tracer = self._server_with_round(obs)
        try:
            status, _, body = _req(srv.port, "/api/v1/debug/rounds")
            assert status == 200
            doc = json.loads(body)
            assert doc["count"] == 1 and doc["ring_size"] == obs.ring.size
            (entry,) = doc["rounds"]
            assert entry["trace_id"] == tracer.trace_id
            assert entry["round_seq"] == 1
            status, headers, body = _req(
                srv.port, f"/api/v1/debug/rounds/{tracer.trace_id}"
            )
            assert status == 200
            assert "application/json" in headers["Content-Type"]
            trace_doc = json.loads(body)
            names = {e["name"] for e in trace_doc["traceEvents"]}
            assert {"fold", "publish", "total"} <= names
        finally:
            srv.close()

    def test_unknown_trace_and_no_obs_answer_404(self):
        obs = Observability()
        srv, _ = self._server_with_round(obs)
        try:
            status, _, body = _req(srv.port, "/api/v1/debug/rounds/deadbeef")
            assert status == 404
            assert "not among" in json.loads(body)["error"]
        finally:
            srv.close()
        bare = FleetStateServer(0, host="127.0.0.1")
        try:
            for path in ("/api/v1/debug/rounds",
                         "/api/v1/debug/rounds/deadbeef"):
                status, _, body = _req(bare.port, path)
                assert status == 404
                assert "tracing not enabled" in json.loads(body)["error"]
        finally:
            bare.close()

    def test_snapshot_reads_carry_round_and_trace_headers(self):
        obs = Observability()
        srv, tracer = self._server_with_round(obs)
        try:
            # Fast path (exact request line) and routed path (per-node)
            # must agree on the identity headers.
            for path in ("/api/v1/nodes", "/api/v1/nodes/n-0"):
                status, headers, _ = _req(srv.port, path)
                assert status == 200, path
                assert headers["X-TNC-Round"] == "1", path
                assert headers["X-TNC-Trace"] == tracer.trace_id, path
        finally:
            srv.close()

    def test_ring_eviction_under_live_hammer(self):
        # Readers poll the debug surface while the round driver pushes
        # completed traces through a small ring: every response parses,
        # eviction never tears a document.
        obs = Observability(ring_size=4)
        srv, _ = self._server_with_round(obs)
        try:
            def swaps():
                for seq in range(2, 30):
                    tracer = obs.tracer(seq)
                    with tracer.span("fold"):
                        pass
                    srv.publish(
                        _Round(_round_payload(trace_id=tracer.trace_id)),
                        tracer=tracer,
                    )
                    obs.complete(tracer)

            flat = fx.hammer_fleet_api(
                srv.port,
                ["/api/v1/debug/rounds", "/api/v1/summary"],
                swaps,
                clients=8,
            )
            fx.assert_poll_contract(flat, bijection=False)
            debug_bodies = [
                body for path, status, _, body in flat
                if path == "/api/v1/debug/rounds" and status == 200
            ]
            assert debug_bodies
            for body in debug_bodies:
                doc = json.loads(body)  # raises on a torn document
                assert len(doc["rounds"]) <= 4
            # After the storm the ring holds exactly the last 4 rounds.
            status, _, body = _req(srv.port, "/api/v1/debug/rounds")
            assert [r["round_seq"] for r in json.loads(body)["rounds"]] == [
                29, 28, 27, 26
            ]
        finally:
            srv.close()

    def test_metrics_expose_bucket_families(self):
        obs = Observability()
        srv, _ = self._server_with_round(obs)
        try:
            _req(srv.port, "/api/v1/nodes/n-0")  # a routed-path sample
            status, _, body = _req(srv.port, "/metrics")
            assert status == 200
            text = body.decode()
            for family in (
                "tpu_node_checker_round_phase_duration_ms",
                "tpu_node_checker_api_server_request_duration_ms",
            ):
                assert f"# TYPE {family} histogram" in text
                assert f'{family}_bucket{{le="+Inf"' in text or (
                    f'{family}_bucket{{' in text
                )
                assert f"{family}_count" in text
            # phase="total" is the whole-round series the bench asserts.
            assert 'phase="total"' in text
            # The deprecated alias is DERIVED from the merged histogram.
            assert ("tpu_node_checker_api_server_request_latency_ms_count"
                    in text)
            assert "DEPRECATED" in text
        finally:
            srv.close()


# ---------------------------------------------------------------------------
# run_check + watch wiring
# ---------------------------------------------------------------------------


class TestRoundTraceWiring:
    def test_run_check_stamps_trace_id(self):
        args = cli.parse_args(["--json"])
        result = checker.run_check(args, nodes=fx.tpu_v5e_single_host())
        assert result.payload["trace_id"]
        assert "timings_ms" in result.payload

    def test_caller_owned_tracer_is_reused(self):
        args = cli.parse_args(["--json"])
        obs = Observability()
        tracer = obs.tracer(11)
        result = checker.run_check(
            args, nodes=fx.tpu_v5e_single_host(), tracer=tracer
        )
        assert result.payload["trace_id"] == tracer.trace_id
        assert "detect" in tracer.phases
        obs.complete(tracer)
        assert obs.ring.find(tracer.trace_id) is tracer
        # The phase histogram saw every phase plus the round total.
        merged = obs.round_phases.merged()
        assert "detect" in merged and "total" in merged

    def test_observability_from_args_reads_cluster_and_event_log(
        self, tmp_path, monkeypatch
    ):
        monkeypatch.delenv("TNC_CLUSTER_NAME", raising=False)
        args = cli.parse_args([
            "--watch", "60", "--cluster-name", "us-a",
            "--event-log", str(tmp_path / "e.jsonl"),
        ])
        obs = Observability.from_args(args)
        assert obs.cluster == "us-a"
        assert obs.events.path == str(tmp_path / "e.jsonl")
        assert obs.events.cluster == "us-a"

    def test_write_audit_line_carries_trace_id(self, capsys):
        obs = Observability()
        srv = FleetStateServer(0, host="127.0.0.1", obs=obs)
        try:
            tracer = obs.tracer(1)
            srv.publish(
                _Round(_round_payload(trace_id=tracer.trace_id)),
                tracer=tracer,
            )
            obs.complete(tracer)
            capsys.readouterr()
            # No token configured → 403 final — and one audit event.
            status, _, _ = _req(srv.port, "/api/v1/nodes/n-0/cordon",
                                method="POST")
            assert status == 403
            lines = [
                json.loads(l)
                for l in capsys.readouterr().err.splitlines()
                if l.startswith("{")
            ]
            (audit,) = [l for l in lines if l["event"] == "fleet-api-write"]
            assert audit["trace_id"] == tracer.trace_id
            assert audit["action"] == "cordon" and audit["node"] == "n-0"
        finally:
            srv.close()

    def test_slack_message_carries_trace_id(self):
        from tpu_node_checker import notify

        posts = []

        def fake_post(url, json=None, timeout=None):
            posts.append(json)

            class R:
                status_code = 200

            return R()

        ok = notify.send_slack_message(
            "https://hooks.example/x", "fleet degraded",
            post=fake_post, trace_id="abc123",
        )
        assert ok
        assert "`trace: abc123`" in posts[0]["text"]


# ---------------------------------------------------------------------------
# CLI validation
# ---------------------------------------------------------------------------


class TestObsCliValidation:
    def test_event_log_requires_a_daemon_mode(self, capsys):
        with pytest.raises(SystemExit):
            cli.parse_args(["--event-log", "/tmp/e.jsonl"])
        assert "--event-log" in capsys.readouterr().err

    def test_event_log_rejected_with_emit_probe(self, capsys):
        with pytest.raises(SystemExit):
            cli.parse_args([
                "--emit-probe", "/tmp/out", "--watch", "60",
                "--event-log", "/tmp/e.jsonl",
            ])
        assert "--event-log" in capsys.readouterr().err

    def test_trace_now_valid_with_federate(self, tmp_path):
        endpoints = tmp_path / "endpoints.json"
        endpoints.write_text(json.dumps({
            "clusters": [{"name": "us-a", "url": "http://127.0.0.1:1"}]
        }))
        args = cli.parse_args([
            "--federate", str(endpoints), "--serve", "0",
            "--trace", str(tmp_path / "t.json"),
            "--event-log", str(tmp_path / "e.jsonl"),
        ])
        assert args.trace and args.event_log

    def test_trace_still_rejected_standalone_serve(self, tmp_path, capsys):
        with pytest.raises(SystemExit):
            cli.parse_args([
                "--serve", "0", "--log-jsonl", str(tmp_path / "r.jsonl"),
                "--trace", str(tmp_path / "t.json"),
            ])
        assert "--trace" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# Federation: one trace spans both tiers
# ---------------------------------------------------------------------------


class TestFederationTwoTierTrace:
    def _upstream(self, cluster="us-a", n=2):
        obs = Observability(cluster=cluster)
        srv = FleetStateServer(0, host="127.0.0.1", obs=obs)
        tracer = obs.tracer(1)
        with tracer.span("fold"):
            pass
        with tracer.span("grade"):
            with tracer.span("detect"):
                pass
        payload = _round_payload(n=n, trace_id=tracer.trace_id,
                                 cluster=cluster)
        with tracer.span("publish"):
            srv.publish(_Round(payload), tracer=tracer)
        obs.complete(tracer)
        return obs, srv, tracer

    def _aggregate(self, tmp_path, servers, extra=()):
        from tpu_node_checker.federation.aggregator import FederationEngine

        endpoints = tmp_path / "endpoints.json"
        endpoints.write_text(json.dumps({
            "clusters": [
                {"name": name, "url": f"http://127.0.0.1:{srv.port}"}
                for name, srv in servers
            ]
        }))
        args = cli.parse_args([
            "--federate", str(endpoints), "--serve", "0",
            "--retry-budget", "0", *extra,
        ])
        obs = Observability()
        agg = FleetStateServer(0, host="127.0.0.1", federation=True, obs=obs)
        engine = FederationEngine(args, obs=obs)
        return obs, agg, engine

    def test_one_trace_id_spans_both_tiers(self, tmp_path):
        up_obs, up_srv, up_tracer = self._upstream()
        obs, agg, engine = self._aggregate(tmp_path, [("us-a", up_srv)])
        try:
            engine.round(agg)
            status, headers, body = _req(agg.port, "/api/v1/global/summary")
            assert status == 200
            trace_id = json.loads(body)["trace_id"]
            assert headers["X-TNC-Trace"] == trace_id
            status, _, body = _req(
                agg.port, f"/api/v1/debug/rounds/{trace_id}"
            )
            assert status == 200
            doc = json.loads(body)
            events = doc["traceEvents"]
            # Tier 1 (the aggregator's own round, pid 1).
            agg_names = {e["name"] for e in events if e.get("pid") == 1}
            assert {"fetch", "merge", "publish", "total"} <= agg_names
            fetch = next(e for e in events
                         if e["name"] == "fetch" and e.get("pid") == 1)
            assert fetch["args"]["cluster"] == "us-a"
            # Tier 2 (the upstream cluster's round, stitched as pid 2).
            track_labels = [
                e["args"]["name"] for e in events
                if e["name"] == "process_name" and e.get("pid") == 2
            ]
            assert track_labels == ["cluster:us-a"]
            up_names = {e["name"] for e in events if e.get("pid") == 2}
            assert {"fold", "grade", "detect", "publish"} <= up_names
            # BOTH trace ids are present in the one document.
            ids = {
                e["args"]["trace_id"] for e in events
                if e["name"] == "trace_id"
            }
            assert ids == {trace_id, up_tracer.trace_id}
            # The list view names the stitched sub-trace too.
            status, _, body = _req(agg.port, "/api/v1/debug/rounds")
            (entry,) = [
                r for r in json.loads(body)["rounds"]
                if r["trace_id"] == trace_id
            ]
            assert entry["subtraces"] == [
                {"label": "cluster:us-a", "trace_id": up_tracer.trace_id}
            ]
        finally:
            up_srv.close()
            agg.close()
            engine.close()

    def test_304_round_reattaches_cached_upstream_trace(self, tmp_path):
        up_obs, up_srv, up_tracer = self._upstream()
        obs, agg, engine = self._aggregate(tmp_path, [("us-a", up_srv)])
        try:
            engine.round(agg)
            engine.round(agg)  # steady: one 304 per endpoint, no re-fetch
            view = engine.views["us-a"]
            assert view.upstream_trace == up_tracer.trace_id
            second = obs.ring.entries()[0]
            assert second.round_seq == 2
            assert second.summary()["subtraces"] == [
                {"label": "cluster:us-a", "trace_id": up_tracer.trace_id}
            ]
        finally:
            up_srv.close()
            agg.close()
            engine.close()

    def test_fetch_histogram_reaches_the_scrape_surface(self, tmp_path):
        up_obs, up_srv, _ = self._upstream()
        obs, agg, engine = self._aggregate(tmp_path, [("us-a", up_srv)])
        try:
            engine.round(agg)
            status, _, body = _req(agg.port, "/metrics")
            assert status == 200
            text = body.decode()
            assert ("# TYPE tpu_node_checker_federation_fetch_duration_ms "
                    "histogram") in text
            assert 'cluster="us-a"' in text
        finally:
            up_srv.close()
            agg.close()
            engine.close()

    def test_shard_transition_events_carry_trace_id(self, tmp_path, capsys):
        up_obs, up_srv, _ = self._upstream()
        obs, agg, engine = self._aggregate(tmp_path, [("us-a", up_srv)])
        try:
            engine.round(agg)
            up_srv.close()  # the cluster goes dark
            capsys.readouterr()
            engine.round(agg)
            lines = [
                json.loads(l)
                for l in capsys.readouterr().err.splitlines()
                if l.startswith("{")
            ]
            (event,) = [l for l in lines if l["event"] == "shard-degraded"]
            assert event["shard"] == "us-a"
            assert event["trace_id"] == engine.last_tracer.trace_id
        finally:
            agg.close()
            engine.close()

    def test_failed_round_trace_is_ring_visible_with_error(self, tmp_path):
        up_obs, up_srv, _ = self._upstream()
        obs, agg, engine = self._aggregate(tmp_path, [("us-a", up_srv)])
        try:
            engine.round(agg)

            def boom(*a, **k):
                raise RuntimeError("merge bug")

            engine._maybe_reload = boom
            with pytest.raises(RuntimeError):
                engine.round(agg)
            failed = obs.ring.entries()[0]
            assert failed.error == "merge bug"
            assert failed.round_seq == 2
        finally:
            up_srv.close()
            agg.close()
            engine.close()


# ---------------------------------------------------------------------------
# Mesh link observability: the _us histogram family, backfilled link spans,
# and the checker-side feed that joins them to probe reports
# ---------------------------------------------------------------------------


def _links_block(entries):
    """entries: {link: (p50_us, verdict)} -> a collective_legs_ok.links dict."""
    return {
        link: {"p50_us": p50, "p99_us": p50 * 2, "budget_us": 400.0,
               "verdict": verdict, "n": 16}
        for link, (p50, verdict) in entries.items()
    }


class TestMeshLinkObservability:
    def test_tuple_label_family_renders_both_labels(self):
        fam = HistogramFamily(
            "tpu_node_checker_mesh_link_duration_us", "per-link sweep",
            (50.0, 500.0), label=("slice", "axis"),
        )
        fam.record(120.0, ("pool/v5e/-", "t1"))
        fam.record(30.0, ("pool/v5e/-", "t0"))
        lines = fam.prometheus_lines()
        joined = "\n".join(lines)
        assert 'axis="t1"' in joined and 'slice="pool/v5e/-"' in joined
        # Both label keys on every bucket line, alongside le.
        bucket = [
            ln for ln in lines
            if ln.startswith("tpu_node_checker_mesh_link_duration_us_bucket")
            and 'axis="t0"' in ln
        ]
        assert bucket and all('slice="pool/v5e/-"' in ln for ln in bucket)
        assert any('le="50"' in ln and ln.endswith(" 1.0") for ln in bucket)

    def test_record_timed_span_lands_in_spans_not_phases(self):
        tracer = Tracer(round_seq=1)
        with tracer.span("probe"):
            tracer.record_timed_span(
                "mesh-link:t1/2", 0.9, verdict="SLOW", budget_us=400.0
            )
        names = [s[0] for s in tracer.spans]
        assert "mesh-link:t1/2" in names
        # Phase names feed the per-phase histogram and the payload timings
        # block — per-link names there would be unbounded-cardinality.
        assert "mesh-link:t1/2" not in tracer.phases
        span = next(s for s in tracer.spans if s[0] == "mesh-link:t1/2")
        assert span[2] == pytest.approx(0.9)
        assert span[1] >= 0.0
        assert span[5] == {"verdict": "SLOW", "budget_us": 400.0}

    def test_observability_mesh_family_scrapes_after_feed(self):
        obs = Observability()
        assert obs.prometheus_lines() == []  # empty family renders nothing
        obs.record_mesh_links([
            ("pool/v5e/-", "t0", 80.0),
            ("pool/v5e/-", "t1", 900.0),
        ])
        joined = "\n".join(obs.prometheus_lines())
        assert "tpu_node_checker_mesh_link_duration_us_bucket" in joined
        assert 'slice="pool/v5e/-",' in joined or '"pool/v5e/-"' in joined
        assert 'axis="t1"' in joined

    def test_emit_link_spans_one_span_per_leg(self):
        tracer = Tracer(round_seq=3)
        probe = {
            "ok": True, "level": "mesh",
            "collective_legs_ok": {
                "links": _links_block({
                    "t0/0": (50.0, "OK"),
                    "t1/2": (900.0, "SLOW"),
                }),
            },
        }
        checker._emit_link_spans(tracer, probe)
        by_name = {s[0]: s for s in tracer.spans}
        assert set(by_name) == {"mesh-link:t0/0", "mesh-link:t1/2"}
        assert by_name["mesh-link:t1/2"][5]["verdict"] == "SLOW"
        assert by_name["mesh-link:t1/2"][2] == pytest.approx(0.9)

    def test_emit_link_spans_tolerates_non_mesh_probes(self):
        tracer = Tracer()
        checker._emit_link_spans(tracer, None)
        checker._emit_link_spans(tracer, {"ok": True})
        checker._emit_link_spans(
            tracer, {"collective_legs_ok": {"t0": True, "t1": True}}
        )
        legacy_timer = PhaseTimer()
        assert hasattr(legacy_timer, "record_timed_span")  # alias of Tracer
        assert tracer.spans == []

    def test_mesh_link_samples_dedupe_per_slice_link(self):
        from tpu_node_checker.detect import select_accelerator_nodes

        nodes = fx.tpu_v5p_64_slice()[:2]
        accel, _ = select_accelerator_nodes(nodes)
        links = _links_block({"t0/0": (60.0, "OK"), "t1/2": (900.0, "SLOW")})
        for n in accel:
            n.probe = {
                "ok": True, "level": "mesh",
                "collective_legs_ok": {"links": dict(links)},
            }
        samples = checker._mesh_link_samples(accel)
        # Both hosts report the SAME sweep: one sample per distinct link,
        # not per host — a big slice must not outweigh a small one.
        assert len(samples) == 2
        domains = {s[0] for s in samples}
        assert len(domains) == 1 and "-" not in domains
        assert {(axis, p50) for _, axis, p50 in samples} == {
            ("t0", 60.0), ("t1", 900.0)
        }
        # Probe-less nodes contribute nothing.
        for n in accel:
            n.probe = None
        assert checker._mesh_link_samples(accel) == []
