"""Docs drift guards: the documented surface IS the implemented surface.

Round-3 shipped a "complete README flag table" (commit e474647) with
nothing keeping it complete: any new argparse flag could land undocumented,
and a renamed flag would leave the README teaching a spelling that errors.
Same class of guard as tests/test_dependency_surface.py, pointed at docs.
"""

from __future__ import annotations

import re
from pathlib import Path

import pytest

from tpu_node_checker import cli

REPO = Path(__file__).resolve().parent.parent


def _parser_flags() -> set:
    # The REAL parsers' actions — no source regex to fall out of sync.
    # The simulate subcommand (`tnc simulate …`) is a second real surface
    # whose flags the README documents in its own section.
    from tpu_node_checker.sim import cli as sim_cli

    return {
        opt
        for parser in (cli.build_parser(), sim_cli.build_parser())
        for action in parser._actions
        for opt in action.option_strings
        if opt.startswith("--")
    }


def test_every_cli_flag_is_documented_in_readme():
    flags = _parser_flags()
    assert flags, "found no flags — the scan itself broke"
    readme = (REPO / "README.md").read_text()
    documented = set(re.findall(r"`(--[a-z][a-z0-9-]*)", readme))
    missing = flags - documented - {"--help"}
    assert not missing, (
        "flags implemented but absent from README.md (add a flag-table row "
        f"or usage example): {sorted(missing)}"
    )


def test_readme_documents_no_phantom_flags():
    # The inverse direction: a doc row for a flag that no longer parses
    # teaches operators a spelling that errors.
    flags = _parser_flags() | {"--help", "--version"}
    readme = (REPO / "README.md").read_text()
    documented = set(re.findall(r"`(--[a-z][a-z0-9-]*)", readme))
    phantom = documented - flags
    assert not phantom, f"README documents flags that do not exist: {sorted(phantom)}"


def _registry_scenarios() -> set:
    from tpu_node_checker.sim.scenarios import SCENARIOS

    return set(SCENARIOS)


def _table_scenarios(text: str, start_pat: str) -> set:
    # First-column names of the markdown table inside one section:
    # rows like "| `flap-storm` | ..." (README) or "| flap-storm | ..."
    # (DESIGN).  Stops at the next "## " heading.
    section = re.split(r"\n## ", text.split(start_pat, 1)[1], 1)[0]
    names = set()
    for m in re.finditer(r"^\|\s*`?([a-z][a-z0-9+-]*)`?\s*\|", section,
                         re.M):
        names.add(m.group(1))
    return names - {"scenario"}  # the header row


@pytest.mark.parametrize("path, heading", [
    ("README.md", "## Chaos simulation"),
    ("docs/DESIGN.md", "## 18."),
])
def test_scenario_table_matches_registry(path, heading):
    # Both directions (the TNC203 pattern, pointed at the scenario grid):
    # an undocumented scenario is invisible to operators; a documented
    # scenario that no longer registers teaches a spelling that errors.
    registry = _registry_scenarios()
    assert registry, "the SCENARIOS registry is empty — the scan broke"
    documented = _table_scenarios((REPO / path).read_text(), heading)
    missing = registry - documented
    assert not missing, (
        f"scenarios registered but absent from the {path} table: "
        f"{sorted(missing)}"
    )
    phantom = documented - registry
    assert not phantom, (
        f"{path} documents scenarios that do not exist: {sorted(phantom)}"
    )


def test_probe_md_documents_every_emitted_key():
    # docs/PROBE.md is the prose twin of probe/schema.py's REPORT_SPEC —
    # a key the schema types but the reference never mentions is invisible
    # to operators reading the docs.
    from tpu_node_checker.probe.schema import REPORT_SPEC

    probe_md = (REPO / "docs" / "PROBE.md").read_text()
    # Keys must appear INSIDE a code span: extract span contents first —
    # a paired-backtick regex over the whole document would also match
    # prose BETWEEN two adjacent spans, and a bare substring would let
    # `ok` ride inside "soak".
    # Fenced ``` blocks first (their triple backticks would invert inline
    # pairing for everything after them), keeping their contents — a key
    # shown in an example JSON block counts as documented.
    fences = re.findall(r"```[a-z]*\n(.*?)```", probe_md, re.S)
    inline_src = re.sub(r"```[a-z]*\n.*?```", "", probe_md, flags=re.S)
    spans = "\n".join(re.findall(r"`([^`]+)`", inline_src) + fences)
    missing = {
        k
        for k in REPORT_SPEC
        if not re.search(rf"\b{re.escape(k)}\b", spans)
    }
    assert not missing, (
        f"probe-report keys typed in REPORT_SPEC but absent from docs/PROBE.md: "
        f"{sorted(missing)}"
    )
