"""Property tests for the mergeable percentile sketch (PR 19's tentpole
primitive).

Two contracts, both seeded and stdlib-only:

* **Exact merge associativity/commutativity** — bucket counts are
  integers and min/max merge by comparison, so ANY merge order over any
  partition of a sample stream must yield byte-identical wire docs and
  therefore identical quantiles.  This is what lets a 100-cluster fan-in
  give every aggregator topology the same answer.
* **Error bound vs the raw-replay oracle** — ``quantile(q)`` is within
  the declared relative error ``alpha`` of the exact
  rank-``max(1, ceil(q*n))`` order statistic, across 1k-round random
  streams from several distributions (the shapes MTTR / repair-age /
  round-duration data actually takes).
"""

import json
import math
import random

import pytest

from tpu_node_checker.analytics.sketch import (
    DEFAULT_ALPHA,
    MIN_TRACKABLE,
    Sketch,
    merge_docs,
    merge_state_docs,
    sketch_of,
)

QS = (0.5, 0.9, 0.99)


def exact_quantile(values, q):
    """The oracle: same rank definition as Sketch.quantile."""
    ordered = sorted(values)
    rank = max(1, math.ceil(q * len(ordered)))
    return ordered[rank - 1]


def within_bound(est, exact, alpha=DEFAULT_ALPHA):
    if exact <= MIN_TRACKABLE:
        return est == 0.0
    return abs(est - exact) <= alpha * exact + 1e-12


def _streams(seed, rounds=1000):
    """Latency-shaped sample streams: lognormal (round durations),
    exponential (MTTR), uniform with zero spikes (repair age)."""
    rng = random.Random(seed)
    return {
        "lognormal": [rng.lognormvariate(3.0, 1.2) for _ in range(rounds)],
        "exponential": [rng.expovariate(1 / 90.0) for _ in range(rounds)],
        "uniform_with_zeros": [
            0.0 if rng.random() < 0.1 else rng.uniform(0.5, 7200.0)
            for _ in range(rounds)
        ],
        "wide_range": [
            10 ** rng.uniform(-3, 6) for _ in range(rounds)
        ],
    }


class TestMergeAlgebra:
    @pytest.mark.parametrize("seed", [7, 23, 1729])
    def test_merge_associative_and_commutative(self, seed):
        rng = random.Random(seed)
        values = [rng.lognormvariate(2.0, 1.5) for _ in range(3000)]
        a = sketch_of(values[:1000])
        b = sketch_of(values[1000:1800])
        c = sketch_of(values[1800:])
        left = a.copy().merge(b.copy().merge(c.copy()))       # a+(b+c)
        right = a.copy().merge(b.copy()).merge(c.copy())      # (a+b)+c
        swapped = c.copy().merge(a.copy()).merge(b.copy())    # (c+a)+b
        # Stronger than quantile equality: the entire wire doc agrees
        # except the float ``sum`` rider (addition order), which the
        # quantile path never reads.
        docs = [sk.to_doc() for sk in (left, right, swapped)]
        for doc in docs:
            doc.pop("sum")
        assert docs[0] == docs[1] == docs[2]
        for q in QS:
            assert left.quantile(q) == right.quantile(q) == swapped.quantile(q)

    def test_merge_order_free_over_many_partitions(self):
        rng = random.Random(99)
        values = [rng.expovariate(1 / 300.0) for _ in range(2000)]
        parts = [values[i::7] for i in range(7)]  # 7 uneven shards
        sketches = [sketch_of(p) for p in parts]
        orderings = [list(range(7)) for _ in range(5)]
        for ordering in orderings[1:]:
            rng.shuffle(ordering)
        results = []
        for ordering in orderings:
            merged = Sketch()
            for i in ordering:
                merged.merge(sketches[i])
            results.append(tuple(merged.quantile(q) for q in QS))
        assert len(set(results)) == 1

    def test_alpha_mismatch_refuses(self):
        with pytest.raises(ValueError):
            Sketch(0.01).merge(Sketch(0.02))


class TestErrorBound:
    @pytest.mark.parametrize("seed", [1, 42, 31337])
    def test_single_sketch_within_declared_bound(self, seed):
        for name, values in _streams(seed).items():
            sk = sketch_of(values)
            for q in QS:
                est = sk.quantile(q)
                exact = exact_quantile(values, q)
                assert within_bound(est, exact), (
                    f"{name} q={q}: sketch {est} vs oracle {exact}"
                )

    @pytest.mark.parametrize("seed", [5, 77])
    def test_merged_matches_raw_replay_oracle(self, seed):
        """The federation claim: merge per-shard sketches, compare the
        MERGED quantiles to the oracle over the UNION of raw samples."""
        rng = random.Random(seed)
        shards = []
        union = []
        for _ in range(10):
            n = rng.randrange(50, 300)
            vals = [rng.lognormvariate(4.0, 1.0) for _ in range(n)]
            shards.append(sketch_of(vals))
            union.extend(vals)
        merged = merge_docs(sk.to_doc() for sk in shards)
        assert merged.total == len(union)
        for q in QS:
            est = merged.quantile(q)
            exact = exact_quantile(union, q)
            assert within_bound(est, exact), (
                f"q={q}: merged {est} vs raw-replay {exact}"
            )

    def test_zeros_and_extremes(self):
        sk = sketch_of([0.0, 0.0, 0.0, 5.0])
        assert sk.quantile(0.5) == 0.0
        assert within_bound(sk.quantile(0.99), 5.0)
        assert sk.min == 0.0 and sk.max == 5.0


class TestWireShape:
    def test_doc_roundtrip_preserves_quantiles(self):
        rng = random.Random(11)
        sk = sketch_of([rng.uniform(0.1, 1000.0) for _ in range(500)])
        doc = json.loads(json.dumps(sk.to_doc()))  # through real JSON
        back = Sketch.from_doc(doc)
        assert back.total == sk.total
        for q in QS:
            assert back.quantile(q) == sk.quantile(q)

    def test_merge_state_docs_restacks(self):
        """Doc-level fan-in re-exports a doc the tier above merges again
        to the same answer as a flat merge (aggregator-of-aggregators)."""
        rng = random.Random(13)
        vals = [[rng.expovariate(1 / 60.0) for _ in range(200)]
                for _ in range(4)]
        docs = [sketch_of(v).to_doc() for v in vals]
        flat = merge_docs(docs)
        mid_a = merge_state_docs(docs[:2])
        mid_b = merge_state_docs(docs[2:])
        stacked = merge_docs([mid_a, mid_b])
        for q in QS:
            assert stacked.quantile(q) == flat.quantile(q)

    def test_malformed_docs_skipped_not_fatal(self):
        good = sketch_of([1.0, 2.0, 3.0]).to_doc()
        merged = merge_docs([
            None, "nonsense", {"alpha": 7}, {"alpha": 0.01, "b": "x"},
            good, {"alpha": 0.05, "n": 1, "b": {"0": 1}},  # alpha mismatch
        ])
        assert merged is not None
        assert merged.total == 3

    def test_from_doc_malformed_returns_none(self):
        assert Sketch.from_doc(None) is None
        assert Sketch.from_doc({"alpha": -1}) is None
        assert Sketch.from_doc([1, 2]) is None
