"""Canned raw-k8s node fixtures for the five BASELINE.json measurement configs.

The reference ships no tests (SURVEY §4); these fixtures implement its implied
"multi-node without a real cluster" strategy: plain dicts shaped like
``GET /api/v1/nodes`` items, one builder per scenario.

Configs (BASELINE.json):
  1. CPU-only cluster                      → exit 2
  2. GKE GPU pool (nvidia.com/gpu=1)       → GPU regression path
  3. TPU v5e-8 single-host                 → google.com/tpu + topology labels
  4. TPU v5p-64 16-host slice              → taints + per-host breakdown
  5. Mixed GPU+TPU, one NotReady TPU host  → exit 3 semantics with --slack-only-on-error

The scriptable fault/watch/storm machinery — ``FaultSchedule``,
``WatchScript``, ``StormSchedule``, the fake-apiserver handlers and the
shared node builders they ride — was promoted to
``tpu_node_checker.sim.fixtures`` (PR 12, the chaos simulator's library
layer) and is re-exported here verbatim, so every existing test keeps
importing ``tests.fixtures`` unchanged.
"""

from __future__ import annotations

import json
import time
from typing import List, Optional

# Promoted to package code (tpu_node_checker/sim/fixtures.py) and
# re-exported: the simulator owns the definitions, the tests keep their
# import surface.  ``_paged_nodelist_body`` keeps its historical underscore
# alias beside the now-public ``paged_nodelist_body``.
from tpu_node_checker.sim.fixtures import (  # noqa: F401
    TPU_TAINT,
    FaultSchedule,
    StormSchedule,
    WatchScript,
    churn_flips,
    fault_scheduled_handler,
    make_node,
    node_list,
    paged_nodelist_body,
    paged_nodelist_body as _paged_nodelist_body,
    paged_nodelist_handler,
    serve_http,
    storm_apiserver,
    storm_available_by_slice,
    watch_bookmark,
    watch_error_gone,
    watch_event,
    watch_nodelist_handler,
)


def cpu_only_cluster(n: int = 3) -> List[dict]:
    """Config 1: kind/minikube-style CPU cluster — zero accelerator nodes."""
    return [make_node(f"kind-worker-{i}") for i in range(n)]


def gpu_pool(n: int = 2, ready: bool = True) -> List[dict]:
    """Config 2: GKE GPU node pool, nvidia.com/gpu=1 per node."""
    return [
        make_node(
            f"gke-gpu-pool-{i}",
            ready=ready,
            allocatable={"nvidia.com/gpu": "1"},
            labels={"cloud.google.com/gke-nodepool": "gpu-pool"},
            taints=[{"key": "nvidia.com/gpu", "value": "present", "effect": "NoSchedule"}],
        )
        for i in range(n)
    ]


def tpu_v5e_single_host() -> List[dict]:
    """Config 3: one v5e host with 8 chips (ct5lp-hightpu-8t, topology 2x4)."""
    return [
        make_node(
            "gke-tpu-v5e-0",
            allocatable={"google.com/tpu": "8"},
            labels={
                "cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice",
                "cloud.google.com/gke-tpu-topology": "2x4",
                "cloud.google.com/gke-nodepool": "v5e-pool",
            },
            taints=[TPU_TAINT],
        )
    ]


def tpu_v5p_64_slice(not_ready: int = 0) -> List[dict]:
    """Config 4: v5p-64 — 64 chips over 16 hosts (4 chips/host, topology 4x4x4)."""
    return [
        make_node(
            f"gke-tpu-v5p-{i}",
            ready=i >= not_ready,
            allocatable={"google.com/tpu": "4"},
            labels={
                "cloud.google.com/gke-tpu-accelerator": "tpu-v5p-slice",
                "cloud.google.com/gke-tpu-topology": "4x4x4",
                "cloud.google.com/gke-nodepool": "v5p-pool",
            },
            taints=[TPU_TAINT],
        )
        for i in range(16)
    ]


def tpu_v5e_256_slice(not_ready: int = 0) -> List[dict]:
    """North-star scale: v5e-256 — 256 chips over 64 hosts (4/host, 16x16)."""
    return [
        make_node(
            f"gke-tpu-v5e256-{i:03d}",
            ready=i >= not_ready,
            allocatable={"google.com/tpu": "4"},
            labels={
                "cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice",
                "cloud.google.com/gke-tpu-topology": "16x16",
                "cloud.google.com/gke-nodepool": "v5e-256-pool",
            },
            taints=[TPU_TAINT],
        )
        for i in range(64)
    ]


def tpu_multislice(
    n_slices: int = 2,
    not_ready: int = 0,
    group: str = "ms-train-1",
    group_label: str = "cloud.google.com/gke-multislice-group",
) -> List[dict]:
    """DCN-joined multislice: ``n_slices`` v5e 4x4 slices (4 hosts × 4 chips
    each) sharing one grouping label; ``not_ready`` hosts of slice 0 are down."""
    nodes = []
    for s in range(n_slices):
        for i in range(4):
            nodes.append(
                make_node(
                    f"gke-tpu-ms{s}-{i}",
                    ready=not (s == 0 and i < not_ready),
                    allocatable={"google.com/tpu": "4"},
                    labels={
                        "cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice",
                        "cloud.google.com/gke-tpu-topology": "4x4",
                        "cloud.google.com/gke-nodepool": f"ms-pool-{s}",
                        group_label: group,
                    },
                    taints=[TPU_TAINT],
                )
            )
    return nodes


def big_mixed_cluster(
    cpu: int = 3000, gpu: int = 1000, tpu_slices: int = 16
) -> List[dict]:
    """Scale config: thousands of nodes, many slices — the LIST payload a
    large production cluster returns.  Each TPU slice is a v5e-256 (64 hosts)
    in its own node pool."""
    nodes = cpu_only_cluster(cpu)
    nodes += [
        make_node(
            f"gke-gpu-big-{i:04d}",
            allocatable={"nvidia.com/gpu": "8"},
            labels={"cloud.google.com/gke-accelerator": "nvidia-h100-80gb"},
        )
        for i in range(gpu)
    ]
    for s in range(tpu_slices):
        nodes += [
            make_node(
                f"gke-tpu-big-{s:02d}-{i:03d}",
                allocatable={"google.com/tpu": "4"},
                labels={
                    "cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice",
                    "cloud.google.com/gke-tpu-topology": "16x16",
                    "cloud.google.com/gke-nodepool": f"v5e-big-pool-{s:02d}",
                },
                taints=[TPU_TAINT],
            )
            for i in range(64)
        ]
    return nodes


def mixed_cluster_one_notready() -> List[dict]:
    """Config 5: GPU pool + v5e slice where one TPU host is NotReady."""
    nodes = gpu_pool(2)
    nodes += [
        make_node(
            f"gke-tpu-mixed-{i}",
            ready=(i != 1),
            allocatable={"google.com/tpu": "4"},
            labels={
                "cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice",
                "cloud.google.com/gke-tpu-topology": "2x4",
                "cloud.google.com/gke-nodepool": "v5e-mixed-pool",
            },
            taints=[TPU_TAINT],
        )
        for i in range(2)
    ]
    nodes += cpu_only_cluster(1)
    return nodes


def self_signed_cert(tmpdir: str):
    """127.0.0.1 cert via the openssl CLI; ``None`` where openssl is absent
    (TLS-dependent fixtures then skip).  Shared with bench.py."""
    import os
    import subprocess

    cert = os.path.join(tmpdir, "cert.pem")
    key = os.path.join(tmpdir, "key.pem")
    try:
        proc = subprocess.run(
            [
                "openssl", "req", "-x509", "-newkey", "rsa:2048",
                "-keyout", key, "-out", cert, "-days", "1", "-nodes",
                "-subj", "/CN=127.0.0.1",
                "-addext", "subjectAltName=IP:127.0.0.1",
            ],
            capture_output=True,
        )
    except OSError:
        return None
    return (cert, key) if proc.returncode == 0 else None


def json_value_strategy(
    text_size: int = 20,
    max_leaves: int = 12,
    allow_nan: bool = True,
    allow_infinity: bool = True,
):
    """One recursive JSON-ish-value hypothesis strategy for every fuzz
    surface (detect totality, report-schema validator, trend reader) —
    three hand-rolled near-copies previously drifted on float/NaN knobs.
    ``allow_nan=False, allow_infinity=False`` yields values that survive a
    strict ``json.dumps`` round-trip.  Lazy import: fixtures is also
    consumed by bench.py, which must not require hypothesis."""
    from hypothesis import strategies as st

    scalars = st.one_of(
        st.none(),
        st.booleans(),
        st.integers(min_value=-(10**18), max_value=10**18),
        st.floats(allow_nan=allow_nan, allow_infinity=allow_infinity),
        st.text(max_size=text_size),
    )
    return st.recursive(
        scalars,
        lambda children: st.one_of(
            st.lists(children, max_size=4),
            st.dictionaries(st.text(max_size=text_size), children, max_size=4),
        ),
        max_leaves=max_leaves,
    )


# ---------------------------------------------------------------------------
# Fleet-API poller hammer (shared by tests/test_server.py, the serving-scale
# tests and bench.py's load harness)
# ---------------------------------------------------------------------------


def hammer_fleet_api(port, paths, swaps, clients=16, reconnect=False,
                     thread_prefix="tnc-test-hammer"):
    """``clients`` keep-alive pollers loop over ``paths`` (re-sending each
    path's last ETag) while ``swaps()`` runs on the caller's thread; returns
    the flat ``[(path, status, etag, body)]`` record list.

    ``reconnect=True`` makes a poller redial on connection loss instead of
    failing — the worker-restart hammer: a killed connection yields no
    record (the in-flight response may be torn), every COMPLETED response
    still lands in the records for the 200/304 contract check.  Without it,
    any client error fails the caller via the returned ``errors`` being
    asserted empty here.
    """
    import http.client
    import threading

    done = threading.Event()
    start = threading.Barrier(clients + 1)
    records = [[] for _ in range(clients)]
    errors = []

    def dial():
        deadline = time.monotonic() + 5.0
        while True:
            try:
                return http.client.HTTPConnection("127.0.0.1", port, timeout=10)
            except OSError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.01)  # tnc: allow-test-wall-clock(bounded 5s redial backoff against a REAL listener mid-restart)

    def worker(slot):
        conn = dial()
        try:
            start.wait(timeout=10)
            last_etag = {}
            while not done.is_set():
                for path in paths:
                    headers = {}
                    if path in last_etag:
                        headers["If-None-Match"] = last_etag[path]
                    try:
                        conn.request("GET", path, headers=headers)
                        resp = conn.getresponse()
                        body = resp.read()
                    except (OSError, http.client.HTTPException):
                        if not reconnect:
                            raise
                        # The worker under this connection was restarted:
                        # drop the in-flight exchange, redial, carry on.
                        try:
                            conn.close()
                        except OSError:
                            pass
                        conn = dial()
                        continue
                    etag = resp.headers.get("ETag")
                    if resp.status == 200 and etag is not None:
                        # ETag-less surfaces (the debug-rounds endpoints)
                        # are hammered unconditionally — never send
                        # 'If-None-Match: None'.
                        last_etag[path] = etag
                    records[slot].append((path, resp.status, etag, body))
        except Exception as exc:  # noqa: BLE001 — surfaced as a failure below
            errors.append(f"client {slot}: {exc!r}")
        finally:
            conn.close()

    threads = [
        threading.Thread(
            target=worker, args=(i,), name=f"{thread_prefix}-{i}", daemon=True
        )
        for i in range(clients)
    ]
    for t in threads:
        t.start()
    start.wait(timeout=10)
    swaps()
    done.set()
    for t in threads:
        t.join(timeout=10)
        assert not t.is_alive(), "hammer client wedged"
    assert not errors, errors
    flat = [r for rec in records for r in rec]
    assert len(flat) > clients, "the hammer never actually hammered"
    return flat


def assert_poll_contract(flat, bijection=True):
    """The serving contract over hammer records: nothing outside 200/304,
    every 200 parses (no torn reads), and — when ``bijection`` — ETag ↔
    body ↔ round is a bijection per path (one ETag never names two bodies
    or spans two rounds)."""
    assert {status for _, status, _, _ in flat} <= {200, 304}, sorted(
        {status for _, status, _, _ in flat}
    )
    etag_to_round = {}
    etag_to_body = {}
    rounds_seen = set()
    for path, status, etag, body in flat:
        if status != 200:
            continue
        doc = json.loads(body)  # raises on a torn body
        if not bijection:
            continue
        rnd = doc["round"]
        rounds_seen.add(rnd)
        key = (path, etag)
        assert etag_to_body.setdefault(key, body) == body
        assert etag_to_round.setdefault(key, rnd) == rnd
    if bijection:
        per_round_etags = {}
        for (path, etag), rnd in etag_to_round.items():
            per_round_etags.setdefault((path, rnd), set()).add(etag)
        assert all(len(v) == 1 for v in per_round_etags.values())
    return rounds_seen
