"""The stdlib .env loader (utils/env.py).

Pins parity with the python-dotenv subset the reference relies on
(check-gpu-node.py:331): basic KEY=VALUE, quoting, and — VERDICT r03
residual #2 — multiline quoted values, escape decoding, and ``${VAR}``
interpolation, which previously failed silently.
"""

import os

import pytest

from tpu_node_checker.utils.env import load_dotenv


@pytest.fixture
def clean_env(monkeypatch):
    for k in ("TNC_A", "TNC_B", "TNC_C", "SLACK_WEBHOOK_URL", "TNC_BASE"):
        monkeypatch.delenv(k, raising=False)
    return monkeypatch


def _load(tmp_path, content):
    p = tmp_path / ".env"
    p.write_text(content)
    return load_dotenv(str(p))


class TestBasics:
    def test_missing_file_returns_false(self, tmp_path):
        assert load_dotenv(str(tmp_path / "nope")) is False

    def test_basic_forms(self, tmp_path, clean_env):
        assert _load(
            tmp_path,
            "# comment\n"
            "TNC_A=plain\n"
            "export TNC_B='single quoted'\n"
            'TNC_C="double quoted"\n',
        )
        assert os.environ["TNC_A"] == "plain"
        assert os.environ["TNC_B"] == "single quoted"
        assert os.environ["TNC_C"] == "double quoted"

    def test_existing_environment_wins(self, tmp_path, clean_env):
        clean_env.setenv("TNC_A", "already")
        _load(tmp_path, "TNC_A=file-value\n")
        assert os.environ["TNC_A"] == "already"

    def test_unquoted_trailing_comment_stripped(self, tmp_path, clean_env):
        _load(tmp_path, "TNC_A=value # not part of it\n")
        assert os.environ["TNC_A"] == "value"

    def test_malformed_line_reported_not_silent(self, tmp_path, clean_env, capsys):
        _load(tmp_path, "JUSTAWORD\nTNC_A=ok\n")
        assert os.environ["TNC_A"] == "ok"
        assert "malformed .env line 1" in capsys.readouterr().err


class TestDotenvParity:
    def test_multiline_double_quoted_value(self, tmp_path, clean_env):
        _load(tmp_path, 'TNC_A="line one\nline two"\nTNC_B=after\n')
        assert os.environ["TNC_A"] == "line one\nline two"
        assert os.environ["TNC_B"] == "after"  # parsing resumes cleanly

    def test_escape_decoding_in_double_quotes_only(self, tmp_path, clean_env):
        _load(tmp_path, 'TNC_A="tab\\there \\"q\\""\nTNC_B=\'raw\\n\'\n')
        assert os.environ["TNC_A"] == 'tab\there "q"'
        assert os.environ["TNC_B"] == "raw\\n"  # single quotes stay literal

    def test_interpolation_from_env_and_earlier_keys(self, tmp_path, clean_env):
        clean_env.setenv("TNC_BASE", "https://hooks.slack.example")
        _load(
            tmp_path,
            "TNC_A=${TNC_BASE}/T000/B000\n"
            'TNC_B="copy of ${TNC_A}"\n'
            "TNC_C='${TNC_A}'\n",
        )
        assert os.environ["TNC_A"] == "https://hooks.slack.example/T000/B000"
        assert os.environ["TNC_B"] == "copy of https://hooks.slack.example/T000/B000"
        assert os.environ["TNC_C"] == "${TNC_A}"  # single quotes: no interpolation

    def test_undefined_interpolation_is_empty(self, tmp_path, clean_env):
        _load(tmp_path, "TNC_A=x${TNC_NOPE}y\n")
        assert os.environ["TNC_A"] == "xy"

    def test_unterminated_quote_loses_only_its_line(self, tmp_path, clean_env, capsys):
        # A typo'd quote must not swallow the rest of the file: a later
        # SLACK_WEBHOOK_URL= still loads, and the loss is reported.
        _load(
            tmp_path,
            'TNC_A="never closed\nTNC_B=ok\nSLACK_WEBHOOK_URL=https://x\n',
        )
        assert "TNC_A" not in os.environ
        assert os.environ["TNC_B"] == "ok"
        assert os.environ["SLACK_WEBHOOK_URL"] == "https://x"
        assert "unterminated quote" in capsys.readouterr().err

    def test_empty_value_line_is_fine(self, tmp_path, clean_env):
        # `KEY=` (stubbing a variable empty) must parse, not crash.
        _load(tmp_path, "TNC_A=\nTNC_B=x\n")
        assert os.environ["TNC_A"] == ""
        assert os.environ["TNC_B"] == "x"
