"""--selftest: the fault-detection pipeline proving itself.

Monitoring that cannot demonstrate it catches faults is untrustworthy; the
drill injects one fault per detector class and verifies each is caught AND
correctly named.  The fast tests here simulate probe outcomes to pin the
orchestration (including the detector-missed failure path); the end-to-end
drill on the live CPU mesh is marked slow.
"""

import json

import pytest

from tpu_node_checker import checker, cli
from tpu_node_checker.probe.liveness import ProbeResult


def _fake_probe(monkeypatch, behavior, calls=None):
    """Install a run_local_probe double that reads the chaos env like the
    real child would and asks ``behavior(env)`` for the report details.
    ``calls`` (optional list) records each invocation's ``timeout_s``."""
    import os

    def fake(level="enumerate", timeout_s=None, topology=None, **kw):
        if calls is not None:
            calls.append(timeout_s)
        env = {k: v for k, v in os.environ.items() if k.startswith("TNC_")}
        ok, details = behavior(env, level)
        return ProbeResult(
            ok=ok, level=level, hostname="h", elapsed_ms=1.0,
            device_count=8, platform="cpu", details=details,
        )

    import tpu_node_checker.probe as probe_pkg

    monkeypatch.setattr(probe_pkg, "run_local_probe", fake, raising=False)


def _healthy_behavior(env, level):
    if "TNC_CHAOS_THROTTLE" in env:
        return False, {
            "matmul_tflops": 0.01,
            "perf_floor": {"failed": ["matmul_tflops"], "ok": False},
            "chaos_injected": {"throttle": "matmul_tflops"},
            "error": "perf_floor: matmul_tflops",
        }
    if "TNC_CHAOS_COLLECTIVE_LEG" in env:
        return False, {
            "collective_legs_ok": {
                "psum_ok": True, "all_gather_ok": False, "reduce_scatter_ok": True,
            },
            "collective_err": "collective mismatch",
        }
    if "TNC_CHAOS_RING_LINK" in env:
        return False, {"ring_bad_links": ["0->1"], "ring_err": "ring"}
    if "TNC_CHAOS_SLICES" in env:
        return False, {
            "fault_domain_ok": {"dcn": False, "t0": True},
            "error": "fault localized to the DCN slice boundary",
        }
    return True, {"matmul_tflops": 1.5}


class TestSelftestOrchestration:
    def test_all_detectors_behave(self, monkeypatch, capsys):
        _fake_probe(monkeypatch, _healthy_behavior)
        code = cli.main(["--selftest", "--json"])
        payload = json.loads(capsys.readouterr().out)
        assert code == 0
        assert payload["all_behaved"] is True
        legs = {x["leg"]: x for x in payload["selftest"]}
        assert set(legs) == {
            "baseline", "throttle", "collective_leg", "ring_link", "dcn",
        }
        assert all(x["behaved"] for x in payload["selftest"])

    def test_missed_detection_fails_the_drill(self, monkeypatch, capsys):
        # The one failure mode the drill exists to expose: a fault injected
        # and NOT caught (probe stays ok) must fail the self-test.
        def blind(env, level):
            if "TNC_CHAOS_RING_LINK" in env:
                return True, {"ring_ok": True}  # detector asleep
            return _healthy_behavior(env, level)

        _fake_probe(monkeypatch, blind)
        code = cli.main(["--selftest"])
        out = capsys.readouterr().out
        assert code == 3
        assert "❌ ring_link" in out
        assert "cannot be trusted" in out

    def test_misnamed_fault_fails_the_drill(self, monkeypatch, capsys):
        # Caught but misattributed (wrong link named) is still a failure:
        # an operator acting on the name would repair the wrong cable.
        def misnaming(env, level):
            if "TNC_CHAOS_RING_LINK" in env:
                return False, {"ring_bad_links": ["3->4"]}
            return _healthy_behavior(env, level)

        _fake_probe(monkeypatch, misnaming)
        code = cli.main(["--selftest", "--json"])
        payload = json.loads(capsys.readouterr().out)
        assert code == 3
        legs = {x["leg"]: x for x in payload["selftest"]}
        assert legs["ring_link"]["behaved"] is False
        assert "3->4" in legs["ring_link"]["detail"]

    def test_sick_baseline_skips_injections(self, monkeypatch, capsys):
        def sick(env, level):
            return False, {"error": "no chips"}

        _fake_probe(monkeypatch, sick)
        code = cli.main(["--selftest", "--json"])
        payload = json.loads(capsys.readouterr().out)
        assert code == 3
        assert [x["leg"] for x in payload["selftest"]] == ["baseline"]

    def test_chaos_env_restored_after_drill(self, monkeypatch, capsys):
        import os

        _fake_probe(monkeypatch, _healthy_behavior)
        assert cli.main(["--selftest", "--json"]) == 0
        capsys.readouterr()
        for var in ("TNC_CHAOS_THROTTLE", "TNC_CHAOS_RING_LINK",
                    "TNC_CHAOS_COLLECTIVE_LEG", "TNC_CHAOS_SLICES",
                    "TNC_CHAOS_AXIS", "TNC_PERF_EXPECT"):
            assert var not in os.environ

    def test_stale_chaos_env_does_not_corrupt_the_drill(
        self, monkeypatch, capsys
    ):
        # An operator's leftover manual-rehearsal export must not make the
        # drill report healthy detectors as failed.
        monkeypatch.setenv("TNC_CHAOS_AXIS", "t4")
        monkeypatch.setenv("TNC_CHAOS_COLLECTIVE_LEG", "psum")
        monkeypatch.setenv("TNC_PERF_EXPECT", '{"matmul_tflops": 1e9}')
        # Non-chaos probe knobs leak the same way (r4 advisor): a forced
        # topology, a 10-minute soak, a regrading floor, or a distributed
        # coordinator would stretch or fail legs just as spuriously.
        stale = {
            "TNC_TOPOLOGY": "4x2",
            "TNC_SOAK_S": "600",
            "TNC_HBM_CAPACITY_FLOOR": "0.99",
            "TNC_PERF_FLOOR_MAX_DISPATCH_MS": "0.0001",
            "TNC_COORDINATOR": "10.0.0.1:9999",
        }
        for k, v in stale.items():
            monkeypatch.setenv(k, v)
        # TNC_SKIP_* host-accommodation knobs are NOT injection state: they
        # route around a known toolchain regression, and the drill's
        # baseline leg must keep honoring them or --selftest fails
        # fleet-wide on hosts that are healthy by the operator's own config.
        monkeypatch.setenv("TNC_SKIP_FLASH_ATTENTION", "1")
        leaked = []
        skip_seen = []

        def strict(env, level):
            leaked.extend(k for k in env if k in stale)
            skip_seen.append("TNC_SKIP_FLASH_ATTENTION" in env)
            return _healthy_behavior(env, level)

        _fake_probe(monkeypatch, strict)
        code = cli.main(["--selftest", "--json"])
        payload = json.loads(capsys.readouterr().out)
        assert code == 0, payload
        assert payload["all_behaved"] is True
        assert leaked == [], f"stale probe knobs leaked into drill legs: {leaked}"
        assert skip_seen and all(skip_seen), "TNC_SKIP_* must survive the clear"
        # And the operator's own environment survives the drill.
        import os

        assert os.environ["TNC_CHAOS_AXIS"] == "t4"
        assert os.environ["TNC_PERF_EXPECT"] == '{"matmul_tflops": 1e9}'
        for k, v in stale.items():
            assert os.environ[k] == v

    def test_throttle_leg_uses_calibrated_expectation(self, monkeypatch, capsys):
        # The throttle leg grades against the host's own figure through the
        # same median+margin path --calibrate uses — restricted to the
        # injected metric so no other metric's jitter can fail the leg.
        import json as _json
        import os

        from tpu_node_checker.probe.floors import DEFAULT_CALIBRATION_MARGIN

        seen = []

        def behavior(env, level):
            if "TNC_CHAOS_THROTTLE" in env:
                seen.append(_json.loads(os.environ["TNC_PERF_EXPECT"]))
            return _healthy_behavior(env, level)

        _fake_probe(monkeypatch, behavior)
        assert cli.main(["--selftest", "--json"]) == 0
        capsys.readouterr()
        # _healthy_behavior's baseline measures matmul_tflops=1.5.
        assert seen == [
            {"matmul_tflops": round(DEFAULT_CALIBRATION_MARGIN * 1.5, 3)}
        ]

    def test_probe_timeout_reaches_every_leg(self, monkeypatch, capsys):
        # The drill's one tuning knob: slow transports (first-compile TPU)
        # need a bigger per-leg budget, and EVERY leg's child must receive
        # it — all 5 legs, or a broken baseline gate hides behind exit 0.
        seen = []
        _fake_probe(monkeypatch, _healthy_behavior, calls=seen)
        assert cli.main(["--selftest", "--json", "--probe-timeout", "450"]) == 0
        capsys.readouterr()
        assert len(seen) == 5
        assert all(t == 450.0 for t in seen)

    def test_lazy_probe_package_attr(self):
        import tpu_node_checker.probe as probe_pkg

        assert callable(probe_pkg.run_local_probe)
        with pytest.raises(AttributeError):
            probe_pkg.no_such_symbol

    def test_runs_alone(self, capsys):
        for extra in (["--probe"], ["--watch", "5"], ["--trend", "f"],
                      ["--emit-probe", "-"], ["--log-jsonl", "x"],
                      ["--probe-topology", "2x4"], ["--strict-slices"],
                      ["--probe-level", "collective"], ["--trace", "t"]):
            with pytest.raises(SystemExit) as exc:
                cli.parse_args(["--selftest", *extra])
            assert exc.value.code == 2, extra
            capsys.readouterr()
        args = cli.parse_args(["--selftest", "--json", "--probe-timeout", "60"])
        assert args.selftest


@pytest.mark.slow
class TestSelftestEndToEnd:
    def test_full_drill_on_cpu_mesh(self, capsys):
        # The real thing: every chaos class through real probe children on
        # the 8-device CPU mesh — caught and named, exit 0.
        code = cli.main(["--selftest", "--json"])
        payload = json.loads(capsys.readouterr().out)
        assert code == 0, payload
        assert payload["all_behaved"] is True
        assert len(payload["selftest"]) == 5
