"""The probe report's formal schema (VERDICT r04 missing #4 / next #7).

The emitter/aggregator contract was version-checked by an int but not
type-checked: a field-type drift inside the same major (ring_bad_links as a
string, matmul_tflops as text) passed silently into grading and metrics.
probe/schema.py is the machine contract; these tests pin both directions —
conforming reports pass untouched, drifted reports are refused with the
field NAMED.
"""

import json
import re
import time
from pathlib import Path

from tests import fixtures as fx
from tpu_node_checker import checker, cli
from tpu_node_checker.probe.liveness import run_local_probe
from tpu_node_checker.probe.schema import (
    REPORT_SPEC,
    as_json_schema,
    validate_report,
)


def args_for(*argv):
    return cli.parse_args(list(argv))


MINIMAL = {"ok": True, "level": "enumerate", "hostname": "h", "elapsed_ms": 1.0}


class TestValidateReport:
    def test_minimal_and_rich_reports_conform(self):
        assert validate_report(MINIMAL) == []
        rich = dict(
            MINIMAL,
            schema=1,
            written_at=time.time(),
            platform="tpu",
            device_count=4,
            device_kinds=["TPU v5e"],
            memory=[{"id": 0, "bytes_in_use": 0, "bytes_limit": 16_000_000_000}],
            hbm_capacity={"generation": "v5e", "expected_gb": 16.0,
                          "fraction": 0.9, "min_gb": 15.5,
                          "failed_devices": [], "ok": True},
            matmul_tflops=180.5,
            perf_floor={"generation": "v5e", "fraction": 0.4,
                        "expected": {"matmul_tflops": 197.0},
                        "measured": {"matmul_tflops": 180.5},
                        "ratios": {"matmul_tflops": 0.916},
                        "failed": [], "ok": True},
            collective_busbw_gbps=None,
            ring_bad_links=["0->1"],
            collective_legs_ok={"psum_ok": True, "all_gather_ok": False,
                                "reduce_scatter_ok": True},
            workload_losses=[2.5, 2.1, 1.8],
            soak={"ok": True, "rounds": 5, "seconds": 10.0,
                  "tflops_min": 170.0, "tflops_median": 180.0,
                  "tflops_max": 181.0, "sustained_ratio": 0.94,
                  "hbm_gbps_min": 700.0, "hbm_gbps_median": 720.0},
        )
        assert validate_report(rich) == []

    def test_type_drift_names_the_field(self):
        drifted = dict(MINIMAL, matmul_tflops="fast")
        (violation,) = validate_report(drifted)
        assert violation.startswith("matmul_tflops:")
        drifted = dict(MINIMAL, ring_bad_links="0->1")  # str, not list
        (violation,) = validate_report(drifted)
        assert violation.startswith("ring_bad_links:")
        drifted = dict(MINIMAL, collective_legs_ok={"psum_ok": "yes"})
        (violation,) = validate_report(drifted)
        assert violation.startswith("collective_legs_ok.psum_ok:")
        drifted = dict(MINIMAL, memory=[{"bytes_limit": "16GB"}])
        (violation,) = validate_report(drifted)
        assert violation.startswith("memory[0].bytes_limit:")

    def test_bool_never_passes_as_number(self):
        (violation,) = validate_report(dict(MINIMAL, matmul_tflops=True))
        assert violation.startswith("matmul_tflops:")

    def test_null_allowed_only_where_documented(self):
        assert validate_report(dict(MINIMAL, collective_busbw_gbps=None)) == []
        (violation,) = validate_report(dict(MINIMAL, matmul_tflops=None))
        assert violation.startswith("matmul_tflops:")

    def test_required_keys(self):
        assert "ok: required key missing" in validate_report({"level": "compute"})
        assert "level: required key missing" in validate_report({"ok": True})

    def test_unknown_keys_are_forward_compatible(self):
        assert validate_report(dict(MINIMAL, a_future_minor_field=123)) == []
        # ...including unknown keys inside objects with typed known keys.
        assert validate_report(
            dict(MINIMAL, soak={"ok": True, "rounds": 1, "seconds": 1.0,
                                "tflops_min": 1.0, "tflops_median": 1.0,
                                "tflops_max": 1.0, "sustained_ratio": 1.0,
                                "hbm_gbps_min": 1.0, "hbm_gbps_median": 1.0,
                                "new_minor_figure": 3.0})
        ) == []

    def test_garbage_never_raises(self):
        assert validate_report(None)
        assert validate_report("report")
        assert validate_report([MINIMAL])
        assert validate_report({1: "x", "ok": True, "level": "z"})

    def test_fuzz_validator_is_total(self):
        # The validator fronts the aggregator: ANY JSON-shaped value —
        # including deeply nested garbage under known keys — must yield a
        # list of strings, never an exception (a crash here would sink the
        # whole check round, not one report).
        from hypothesis import given, settings
        from hypothesis import strategies as st

        json_vals = fx.json_value_strategy(text_size=8, max_leaves=12)
        spec_keys = st.dictionaries(
            st.sampled_from(sorted(REPORT_SPEC)), json_vals, max_size=6
        )

        @settings(max_examples=150, deadline=None)
        @given(st.one_of(json_vals, spec_keys))
        def run(doc):
            out = validate_report(doc)
            assert isinstance(out, list)
            assert all(isinstance(v, str) for v in out)

        run()

    def test_spec_covers_every_emitted_key(self):
        # Lockstep guard: any new out["key"] in the probe child must be
        # added to REPORT_SPEC (and docs/PROBE.md) or this fails.
        src = Path(checker.__file__).parent / "probe" / "liveness.py"
        emitted = set(re.findall(r'out\["([a-z_0-9]+)"\]', src.read_text()))
        missing = emitted - set(REPORT_SPEC)
        assert not missing, f"probe keys not in REPORT_SPEC: {sorted(missing)}"

    def test_real_probe_report_conforms(self, shared_compute_probe):
        doc = shared_compute_probe.to_dict()
        doc["schema"] = 1
        doc["written_at"] = time.time()
        assert validate_report(doc) == []

    def test_failed_leg_nulls_still_conform(self):
        # When a per-axis leg crashes before producing a verdict, liveness
        # emits null for the verdict/topology keys ((ax.details or
        # {}).get(...)).  Such FAILED-probe reports must attach and degrade
        # the host — refusing them as drifted would let a sick host keep
        # its healthy kubelet grade.
        failed = dict(
            MINIMAL, ok=False, error="ici axis leg crashed",
            ici_axis_ok=None, ici_topology=None,
            fault_domain_ok=None, fault_domain_topology=None,
        )
        assert validate_report(failed) == []
        # The populated shapes still conform — and still drift-check.
        assert validate_report(
            dict(MINIMAL, ici_axis_ok={"t0": True, "t1": False})
        ) == []
        (violation,) = validate_report(dict(MINIMAL, ici_axis_ok={"t0": "yes"}))
        assert violation.startswith("ici_axis_ok.t0:")
        (violation,) = validate_report(dict(MINIMAL, ici_axis_ok=[True]))
        assert violation.startswith("ici_axis_ok:")

    def test_crashed_collective_leg_nulls_still_conform(self):
        # ADVICE r5 high: a CRASHED collective probe (details=None in
        # parallel/collectives.py) emits {psum_ok: None, all_gather_ok:
        # None, reduce_scatter_ok: None} — the exact shape liveness.py
        # builds via (coll.details or {}).get(k).  Bool-only value specs
        # rejected the whole report, and the host silently graded HEALTHY.
        crashed = dict(
            MINIMAL, ok=False, level="collective",
            collective_ok=False,
            collective_err="RuntimeError: collective probe crashed",
            collective_legs_ok={
                "psum_ok": None, "all_gather_ok": None, "reduce_scatter_ok": None,
            },
        )
        assert validate_report(crashed) == []
        # Populated verdicts still conform — and still drift-check.
        assert validate_report(
            dict(MINIMAL, collective_legs_ok={"psum_ok": True, "all_gather_ok": False})
        ) == []
        (violation,) = validate_report(
            dict(MINIMAL, collective_legs_ok={"psum_ok": "yes"})
        )
        assert violation.startswith("collective_legs_ok.psum_ok:")

    def test_strict_mode_off_spellings(self, monkeypatch):
        # An exported TNC_SCHEMA_STRICT=0 selects the documented warn-only
        # production behavior — it must not read as "strict".
        from tpu_node_checker.probe.schema import strict_mode

        for off in ("", "0", "false", "False", "no"):
            monkeypatch.setenv("TNC_SCHEMA_STRICT", off)
            assert strict_mode() is False, off
        for on in ("1", "true", "yes"):
            monkeypatch.setenv("TNC_SCHEMA_STRICT", on)
            assert strict_mode() is True, on

    def test_json_schema_document(self):
        doc = as_json_schema()
        assert doc["required"] == ["ok", "level"]
        assert set(doc["properties"]) == set(REPORT_SPEC)
        json.dumps(doc)  # serializable end to end
        assert doc["properties"]["collective_busbw_gbps"]["anyOf"]
        assert doc["properties"]["memory"]["items"]["properties"]["bytes_limit"]


class TestSchemaCliExport:
    def test_prints_the_document_and_runs_alone(self, capsys):
        import pytest

        assert cli.main(["--probe-report-schema"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["$schema"].startswith("https://json-schema.org/")
        assert set(doc["properties"]) == set(REPORT_SPEC)
        for argv in (
            ["--probe-report-schema", "--json"],
            ["--probe-report-schema", "--probe"],
            ["--probe-report-schema", "--watch", "5"],
            ["--probe-report-schema", "--slack-webhook", "https://x"],
            # Caught via parser defaults, not a hand-kept name list: a
            # zero value and an explicitly-set truthy-default flag both
            # differ from their defaults.
            ["--probe-report-schema", "--probe-timeout", "0"],
            ["--probe-report-schema", "--slack-retry-count", "5"],
        ):
            with pytest.raises(SystemExit) as e:
                cli.parse_args(argv)
            assert e.value.code == 2, argv
            capsys.readouterr()


class TestAggregatorRefusal:
    def _write_report(self, directory, hostname, **overrides):
        doc = {
            "ok": True, "level": "compute", "hostname": hostname,
            "elapsed_ms": 5.0, "schema": 1, "written_at": time.time(),
            "device_count": 4,
        }
        doc.update(overrides)
        (directory / f"{hostname}.json").write_text(json.dumps(doc))

    def test_type_drifted_report_refused_with_named_field(
        self, tmp_path, capsys
    ):
        nodes = fx.tpu_v5e_single_host()
        host = nodes[0]["metadata"]["name"]
        self._write_report(tmp_path, host, matmul_tflops="fast")
        result = checker.run_check(
            args_for(
                "--probe-results", str(tmp_path),
                "--probe-results-required", "--json",
            ),
            nodes=nodes,
        )
        err = capsys.readouterr().err
        assert "schema violation" in err and "matmul_tflops" in err
        # Refused ⇒ the host graded MISSING (safe direction), counted under
        # the same contract-break counter as version skew.
        assert result.payload["probe_summary"]["hosts_missing"] == [host]
        assert result.payload["probe_summary"]["reports_skipped"]["schema"] == 1

    def test_conforming_report_attaches(self, tmp_path, capsys):
        nodes = fx.tpu_v5e_single_host()
        host = nodes[0]["metadata"]["name"]
        self._write_report(tmp_path, host, matmul_tflops=180.5)
        result = checker.run_check(
            args_for("--probe-results", str(tmp_path), "--json"), nodes=nodes
        )
        assert result.payload["probe_summary"]["hosts_ok"] == 1
        capsys.readouterr()


class TestEmitterStrictness:
    def test_emitter_validates_its_own_report(self, tmp_path, monkeypatch, capsys):
        from tpu_node_checker.probe.liveness import ProbeResult

        monkeypatch.setattr(
            "tpu_node_checker.probe.run_local_probe",
            lambda **kw: ProbeResult(
                ok=True, level="compute", hostname="h", elapsed_ms=1.0,
                device_count=4, details={"matmul_tflops": "fast"},  # drifted
            ),
        )
        # Strict (the suite's default): the bug fails loudly, nothing written.
        out = tmp_path / "r.json"
        code = cli.main(["--emit-probe", str(out)])
        captured = capsys.readouterr()
        assert code == 1
        assert "matmul_tflops" in (captured.out + captured.err)
        assert not out.exists()
        # Production (no TNC_SCHEMA_STRICT): warn on stderr, still emit — a
        # schema lagging a hotfix must not silence a healthy fleet.
        monkeypatch.delenv("TNC_SCHEMA_STRICT")
        code = cli.main(["--emit-probe", str(out)])
        captured = capsys.readouterr()
        assert code == 0
        assert "WARNING" in captured.err and "matmul_tflops" in captured.err
        assert json.loads(out.read_text())["matmul_tflops"] == "fast"
