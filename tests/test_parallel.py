"""Mesh + ICI collective probe tests over the virtual 8-device CPU mesh
(conftest forces ``--xla_force_host_platform_device_count=8``)."""

import jax
import pytest

from tpu_node_checker.parallel import (
    MeshSpec,
    build_mesh,
    collective_probe,
    mesh_from_topology,
    per_axis_probe,
    ring_probe,
)


def test_virtual_mesh_available():
    assert len(jax.devices()) == 8


class TestMeshBuild:
    def test_flat_mesh(self):
        mesh = build_mesh(MeshSpec((("d", 8),)))
        assert mesh.axis_names == ("d",)
        assert mesh.devices.shape == (8,)

    def test_2d_mesh(self):
        mesh = build_mesh(MeshSpec((("data", 4), ("model", 2))))
        assert mesh.devices.shape == (4, 2)

    def test_wrong_device_count_raises(self):
        with pytest.raises(ValueError, match="needs 16 devices"):
            build_mesh(MeshSpec((("d", 16),)))

    def test_mesh_from_topology_label(self):
        mesh = mesh_from_topology("2x4")
        assert mesh.devices.shape == (2, 4)
        assert mesh.axis_names == ("t0", "t1")

    def test_mesh_from_topology_mismatch_falls_back_flat(self):
        mesh = mesh_from_topology("16x16")  # promises 256, we have 8
        assert mesh.devices.shape == (8,)

    def test_mesh_from_topology_none(self):
        assert mesh_from_topology(None).devices.shape == (8,)


class TestCollectiveProbe:
    def test_psum_all_gather_all_devices(self):
        r = collective_probe(payload=64, timed_iters=2)
        assert r.ok, r.error
        assert r.n_devices == 8
        assert r.details["psum_ok"] is True
        assert r.details["all_gather_ok"] is True
        assert r.details["reduce_scatter_ok"] is True
        assert r.details["busbw_gbps"] >= 0
        assert r.latency_us > 0

    def test_over_2d_mesh_flattened(self):
        mesh = build_mesh(MeshSpec((("x", 2), ("y", 4))))
        r = collective_probe(mesh=mesh, payload=32, timed_iters=1)
        assert r.ok, r.error
        assert r.n_devices == 8

    def test_subset_mesh(self):
        mesh = build_mesh(MeshSpec((("d", 4),)), jax.devices()[:4])
        r = collective_probe(mesh=mesh, payload=32, timed_iters=1)
        assert r.ok, r.error
        assert r.n_devices == 4


class TestPerAxisProbe:
    def test_topology_2x4(self):
        r = per_axis_probe(topology="2x4", payload=16)
        assert r.ok, r.error
        assert r.n_devices == 8
        assert r.details["topology"] == "2x4"
        assert r.details["axis_ok"] == {"t0": True, "t1": True}

    def test_topology_2x2x2(self):
        r = per_axis_probe(topology="2x2x2", payload=8)
        assert r.ok, r.error
        assert r.details["axis_ok"] == {"t0": True, "t1": True, "t2": True}

    def test_explicit_mesh(self):
        mesh = build_mesh(MeshSpec((("x", 4), ("y", 2))))
        r = per_axis_probe(mesh=mesh, payload=8)
        assert r.ok, r.error
        assert r.details["axis_ok"] == {"x": True, "y": True}

    def test_mismatched_topology_degrades_flat(self):
        # Label promises 256 chips, mesh has 8 → flat single-axis fallback.
        r = per_axis_probe(topology="16x16", payload=8)
        assert r.ok, r.error
        assert r.details["topology"] == "8"
        assert list(r.details["axis_ok"]) == ["d"]

    def test_never_raises(self):
        r = per_axis_probe(payload=-1)
        assert not r.ok
        assert r.error

    def test_injected_fault_localizes_to_its_axis_only(self):
        # The localization CONTRACT: a fault on t1 is reported as t1 and
        # nothing else — exercised via the chaos hook since real CPU "ICI"
        # cannot be corrupted.
        r = per_axis_probe(topology="2x4", payload=8, inject_fault_axis="t1")
        assert not r.ok
        assert r.details["axis_ok"] == {"t0": True, "t1": False}
        assert "t1=4" in r.error
        assert "t0" not in r.error

    def test_injecting_into_unknown_axis_fails_loudly(self):
        # Topology 16x16 mismatches 8 devices → flat fallback axis "d";
        # injecting into the now-nonexistent t1 must NOT silently pass.
        r = per_axis_probe(topology="16x16", payload=8, inject_fault_axis="t1")
        assert not r.ok
        assert "not in mesh axes" in r.error


class TestRingProbe:
    def test_full_ring(self):
        r = ring_probe(payload=32)
        assert r.ok, r.error
        assert r.n_devices == 8
        assert r.details["hops"] == 8
        assert r.details["link_gbps"] >= 0

    def test_ring_over_2d_mesh(self):
        mesh = build_mesh(MeshSpec((("x", 4), ("y", 2))))
        r = ring_probe(mesh=mesh, payload=16)
        assert r.ok, r.error
