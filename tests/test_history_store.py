"""History store tests: round-trip, bounds, compaction, torn-line tolerance.

Includes the tier-1 property test (seeded stdlib ``random`` — no external
fuzzing dependency): arbitrary append sequences must round-trip through
load, survive compaction byte-for-byte in content, and never lose more
than the bound says they may.
"""

import json
import random

import pytest

from tpu_node_checker.history.store import (
    DEFAULT_MAX_ROUNDS,
    HISTORY_SCHEMA_VERSION,
    HistoryStore,
    read_jsonl_tolerant,
)


def _entry(node, i, ok=True, **extra):
    return {
        "node": node,
        "ts": 1_700_000_000.0 + i,
        "ok": ok,
        "causes": [] if ok else ["probe-failed"],
        "state": "HEALTHY" if ok else "SUSPECT",
        "streak": 1,
        "flaps": 0,
        "flaps_total": 0,
        **extra,
    }


class TestReadJsonlTolerant:
    def test_skips_torn_final_line(self, tmp_path):
        p = tmp_path / "h.jsonl"
        p.write_text(json.dumps({"a": 1}) + "\n" + '{"torn": tru')
        entries, skipped = read_jsonl_tolerant(str(p))
        assert entries == [{"a": 1}]
        assert skipped == 1

    def test_whitespace_only_file_is_empty_not_error(self, tmp_path):
        p = tmp_path / "h.jsonl"
        p.write_text("\n   \n\t\n")
        assert read_jsonl_tolerant(str(p)) == ([], 0)

    def test_non_dict_roots_are_skipped(self, tmp_path):
        # "3" and "[1]" are valid JSON; every consumer indexes by key.
        p = tmp_path / "h.jsonl"
        p.write_text('3\n[1, 2]\n{"ok": true}\n')
        entries, skipped = read_jsonl_tolerant(str(p))
        assert entries == [{"ok": True}]
        assert skipped == 2

    def test_garbage_mid_file_costs_only_its_line(self, tmp_path):
        p = tmp_path / "h.jsonl"
        p.write_text('{"a": 1}\nnot json at all\n{"b": 2}\n')
        entries, skipped = read_jsonl_tolerant(str(p))
        assert entries == [{"a": 1}, {"b": 2}]
        assert skipped == 1

    def test_missing_file_raises_for_caller_policy(self, tmp_path):
        with pytest.raises(OSError):
            read_jsonl_tolerant(str(tmp_path / "absent.jsonl"))


class TestHistoryStore:
    def test_round_trip(self, tmp_path):
        path = str(tmp_path / "h.jsonl")
        store = HistoryStore(path)
        for i in range(3):
            store.record(_entry("tpu-0", i))
        store.record(_entry("tpu-1", 0, ok=False))
        store.flush()
        fresh = HistoryStore(path)
        by_node = fresh.load()
        assert set(by_node) == {"tpu-0", "tpu-1"}
        assert len(by_node["tpu-0"]) == 3
        assert by_node["tpu-1"][0]["ok"] is False
        # Every persisted line carries the schema major.
        assert all(
            e["schema"] == HISTORY_SCHEMA_VERSION
            for tail in by_node.values()
            for e in tail
        )

    def test_missing_file_loads_empty(self, tmp_path):
        store = HistoryStore(str(tmp_path / "absent.jsonl"))
        assert store.load() == {}

    def test_load_bounds_per_node_tail(self, tmp_path):
        path = str(tmp_path / "h.jsonl")
        with open(path, "w") as f:
            for i in range(50):
                f.write(json.dumps(_entry("tpu-0", i)) + "\n")
        store = HistoryStore(path, max_rounds=8)
        by_node = store.load()
        assert len(by_node["tpu-0"]) == 8
        assert by_node["tpu-0"][-1]["ts"] == 1_700_000_000.0 + 49

    def test_torn_final_line_tolerated(self, tmp_path):
        path = str(tmp_path / "h.jsonl")
        store = HistoryStore(path)
        store.record(_entry("tpu-0", 0))
        store.flush()
        with open(path, "a") as f:
            f.write('{"node": "tpu-0", "ts": 1700000001.0, "ok": tr')  # crash
        fresh = HistoryStore(path)
        by_node = fresh.load()
        assert len(by_node["tpu-0"]) == 1
        assert fresh.skipped_lines == 1

    def test_future_schema_major_refused_not_misread(self, tmp_path, capsys):
        path = str(tmp_path / "h.jsonl")
        with open(path, "w") as f:
            f.write(json.dumps(_entry("tpu-0", 0)) + "\n")
            f.write(
                json.dumps(
                    {**_entry("tpu-0", 1), "schema": HISTORY_SCHEMA_VERSION + 1}
                )
                + "\n"
            )
        store = HistoryStore(path)
        by_node = store.load()
        assert len(by_node["tpu-0"]) == 1  # the foreign line did not load
        assert store.refused_lines == 1
        assert "schema major" in capsys.readouterr().err

    def test_schemaless_line_accepted(self, tmp_path):
        # Pre-versioning lines (no "schema" key) keep loading — the same
        # rolling-upgrade posture as the probe report contract.
        path = str(tmp_path / "h.jsonl")
        with open(path, "w") as f:
            f.write(json.dumps(_entry("tpu-0", 0)) + "\n")
        assert "schema" not in json.loads(open(path).read())
        assert len(HistoryStore(path).load()["tpu-0"]) == 1

    def test_compaction_is_atomic_and_bounded(self, tmp_path):
        path = str(tmp_path / "h.jsonl")
        store = HistoryStore(path, max_rounds=4)
        # Enough appends to blow way past the 2×bound threshold (min 256).
        for i in range(400):
            store.record(_entry("tpu-0", i, ok=(i % 2 == 0)))
            store.flush()
        lines = open(path).read().splitlines()
        assert len(lines) <= 256  # compacted, not still 400 lines
        assert not (tmp_path / "h.jsonl.tmp").exists()
        by_node = HistoryStore(path, max_rounds=4).load()
        assert len(by_node["tpu-0"]) == 4
        assert by_node["tpu-0"][-1]["ts"] == 1_700_000_000.0 + 399

    def test_write_failure_is_not_fatal(self, tmp_path, capsys):
        store = HistoryStore(str(tmp_path))  # a DIRECTORY: open() will fail
        store.record(_entry("tpu-0", 0))
        store.flush()  # must not raise
        assert "Cannot append history store" in capsys.readouterr().err


class TestStoreProperty:
    """Tier-1 round-trip + compaction property test (seeded, no deps)."""

    def test_random_append_reload_compact_invariants(self, tmp_path):
        rng = random.Random(0xC0FFEE)
        for case in range(10):
            path = str(tmp_path / f"h{case}.jsonl")
            max_rounds = rng.randint(1, 12)
            nodes = [f"n{i}" for i in range(rng.randint(1, 5))]
            expected = {}
            store = HistoryStore(path, max_rounds=max_rounds)
            store.load()
            ticks = rng.randint(1, 120)
            for t in range(ticks):
                for node in nodes:
                    if rng.random() < 0.7:
                        e = _entry(node, t, ok=rng.random() < 0.5)
                        store.record(e)
                        expected.setdefault(node, []).append(
                            {"schema": HISTORY_SCHEMA_VERSION, **e}
                        )
                store.flush()
                if rng.random() < 0.1:
                    # Mid-history process restart: reload from disk.
                    store = HistoryStore(path, max_rounds=max_rounds)
                    store.load()
            # Invariant 1: a fresh load reproduces exactly the bounded tail
            # of everything recorded, in order.
            loaded = HistoryStore(path, max_rounds=max_rounds).load()
            for node, seq in expected.items():
                assert loaded.get(node) == seq[-max_rounds:], (
                    f"case {case} node {node}"
                )
            # Invariant 2: explicit compaction changes nothing observable.
            store = HistoryStore(path, max_rounds=max_rounds)
            store.load()
            store.compact()
            recompacted = HistoryStore(path, max_rounds=max_rounds).load()
            for node, seq in expected.items():
                assert recompacted.get(node) == seq[-max_rounds:]
            # Invariant 3: the file never holds more than the compaction
            # bound allows right after a compaction.
            assert len(open(path).read().splitlines()) <= max_rounds * len(nodes)

    def test_default_bound_is_sane(self):
        assert DEFAULT_MAX_ROUNDS >= 10
