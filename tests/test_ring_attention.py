"""Ring attention tests over the 8-device CPU mesh: numerics vs reference."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from tpu_node_checker.parallel import (
    MeshSpec,
    build_mesh,
    make_ring_attention,
    reference_causal_attention,
    ring_attention_probe,
)


class TestRingAttentionProbe:
    def test_full_ring_matches_reference(self):
        r = ring_attention_probe(seq_per_device=16)
        assert r.ok, r.error
        assert r.n_devices == 8
        assert r.seq_len == 128
        assert r.max_abs_err < 1e-3

    def test_subset_ring(self):
        mesh = build_mesh(MeshSpec((("sp", 4),)), jax.devices()[:4])
        r = ring_attention_probe(mesh=mesh, seq_per_device=8)
        assert r.ok, r.error
        assert r.n_devices == 4

    def test_probe_never_raises(self):
        # head_dim of 0 is invalid; must degrade, not raise.
        r = ring_attention_probe(head_dim=0)
        assert not r.ok


class TestRingAttentionFn:
    def test_bf16_inputs(self):
        mesh = build_mesh(MeshSpec((("sp", 8),)))
        S = 8 * 8
        shape = (1, S, 2, 16)
        ks = jax.random.split(jax.random.PRNGKey(3), 3)
        q, k, v = (jax.random.normal(kk, shape, jnp.bfloat16) for kk in ks)
        spec = NamedSharding(mesh, P(None, "sp", None, None))
        out = make_ring_attention(mesh)(
            *(jax.device_put(x, spec) for x in (q, k, v))
        )
        assert out.dtype == jnp.bfloat16
        ref = reference_causal_attention(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=3e-2
        )

    def test_causality_first_block(self):
        # Device 0's output depends only on its own block: zeroing later K/V
        # blocks must not change the first block's output.
        mesh = build_mesh(MeshSpec((("sp", 8),)))
        S, per = 64, 8
        shape = (1, S, 1, 8)
        ks = jax.random.split(jax.random.PRNGKey(4), 3)
        q, k, v = (jax.random.normal(kk, shape, jnp.float32) for kk in ks)
        spec = NamedSharding(mesh, P(None, "sp", None, None))
        fn = make_ring_attention(mesh)
        out_a = fn(*(jax.device_put(x, spec) for x in (q, k, v)))
        k2 = k.at[:, per:].set(0.0)
        v2 = v.at[:, per:].set(0.0)
        out_b = fn(*(jax.device_put(x, spec) for x in (q, k2, v2)))
        np.testing.assert_allclose(
            np.asarray(out_a)[:, :per], np.asarray(out_b)[:, :per], rtol=1e-5
        )
