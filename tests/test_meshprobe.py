"""Mesh link doctor: per-link sweep grading on the 8-device CPU mesh.

Mirrors test_chaos_hooks.py: every injection must be *named* (exactly the
injected leg, nothing else), typos must fail loudly, and the no-injection
sweep must be healthy, complete (n_links == the topology-derived
expectation) and deterministically ordered — the contracts the bench
series and the degraded-link sim scenario build on.
"""

from __future__ import annotations

import pytest

from tpu_node_checker.meshprobe import (
    DEAD,
    OK,
    SLOW,
    MeshLinkReport,
    expected_link_count,
    link_names,
    mesh_link_sweep,
    qualify_link,
)

N = 8  # conftest virtual CPU devices


class TestLinkNaming:
    def test_expected_link_count_topology(self):
        # One leg per ring hop: 2x4 → 2 + 4.
        assert expected_link_count("2x4", N) == 6
        assert link_names("2x4", N) == [
            "t0/0", "t0/1", "t1/0", "t1/1", "t1/2", "t1/3",
        ]

    def test_flat_fallback_and_degenerate(self):
        # No/mismatched label → one flat ring of n legs; 1 device → none.
        assert expected_link_count(None, N) == N
        assert expected_link_count("4x4", N) == N  # label ≠ device count
        assert expected_link_count(None, 1) == 0
        assert link_names(None, 3) == ["d/0", "d/1", "d/2"]

    def test_qualify_link_joins_domain_namespace(self):
        assert qualify_link("pool-a", "t1/2") == "pool-a/t1/2"
        assert qualify_link(None, "t1/2") == "t1/2"


class TestMeshLinkSweep:
    def test_healthy_sweep_complete_and_ordered(self):
        r = mesh_link_sweep(topology="2x4", payload=16, hop_iters=3)
        assert r.ok and not r.degraded and r.error is None
        assert r.n_devices == N
        assert r.n_links == expected_link_count("2x4", N)
        assert list(r.links) == link_names("2x4", N)
        assert all(v["verdict"] == OK for v in r.links.values())
        assert all(
            v["p50_us"] <= v["p99_us"] and v["budget_us"] > 0
            for v in r.links.values()
        )
        assert r.slow == [] and r.dead == []

    def test_flat_ring_without_topology(self):
        r = mesh_link_sweep(payload=16, hop_iters=3)
        assert r.ok
        assert list(r.links) == link_names(None, N)

    def test_deterministic_naming_across_runs(self):
        a = mesh_link_sweep(topology="2x4", payload=16, hop_iters=3)
        b = mesh_link_sweep(topology="2x4", payload=16, hop_iters=3)
        assert list(a.links) == list(b.links)
        assert [v["verdict"] for v in a.links.values()] == [
            v["verdict"] for v in b.links.values()
        ]

    def test_slow_injection_names_exactly_that_link(self):
        r = mesh_link_sweep(
            topology="2x4", payload=16, hop_iters=3, inject_slow_link="t1:2"
        )
        # SLOW degrades, never fails: the probe's ok verdict must not change.
        assert r.ok and r.degraded and r.error is None
        assert r.slow == ["t1/2"] and r.dead == []
        assert r.links["t1/2"]["verdict"] == SLOW
        assert r.links["t1/2"]["p50_us"] > r.links["t1/2"]["budget_us"]
        assert all(
            v["verdict"] == OK for k, v in r.links.items() if k != "t1/2"
        )

    def test_dead_injection_fails_and_names(self):
        r = mesh_link_sweep(
            topology="2x4", payload=16, hop_iters=3, inject_dead_link="t0:1"
        )
        assert not r.ok
        assert r.dead == ["t0/1"]
        assert r.links["t0/1"]["verdict"] == DEAD
        assert "t0/1" in r.error
        assert all(
            v["verdict"] == OK for k, v in r.links.items() if k != "t0/1"
        )

    @pytest.mark.parametrize(
        "spec,needle",
        [
            ("zz:0", "axis 'zz'"),
            ("t1:9", "out of range"),
            ("t1", "must be 'axis:hop'"),
            ("t1:x", "not an integer"),
        ],
    )
    def test_typo_injection_fails_loudly(self, spec, needle):
        # Never-inject-nothing-silently: the chaos-hook contract.
        r = mesh_link_sweep(topology="2x4", payload=16, hop_iters=1,
                            inject_slow_link=spec)
        assert not r.ok
        assert needle in r.error

    def test_report_never_raises(self):
        # A broken mesh argument degrades to a structured failure.
        r = mesh_link_sweep(mesh=object(), payload=16, hop_iters=1)
        assert isinstance(r, MeshLinkReport)
        assert not r.ok and r.error
