"""The chaos fuzzer and its shrinker: determinism, minimality, bite.

The fuzzer's whole value is that a red seed is a PERMANENT artifact —
which only holds if sampling, execution, and shrinking are all pure
functions of their inputs.  These tests pin that: same seed, same
program; same campaign, same bytes; same sabotage, same minimal
reproducer.  The sabotage path reuses the ``test_simulate.py`` matrix-
bite technique (an unbudgeted fleet-wide cordon) so the shrinker is
proven against a violation the matrix is already known to catch.
"""

from __future__ import annotations

import json
import os

import pytest

from tpu_node_checker import checker
from tpu_node_checker.sim import cli as sim_cli
from tpu_node_checker.sim import fuzz
from tpu_node_checker.sim.engine import ScenarioError

REPRO_DIR = os.path.join(os.path.dirname(__file__), "sim_reproducers")


def _sabotage_program() -> dict:
    """A small sabotaged world with shrinkable decoys: one failure
    program, one API fault, and a fleet/round surplus — everything except
    the sabotage itself must shrink away."""
    return {
        "slices": 2,
        "hosts_per_slice": 4,
        "rounds": 3,
        "programs": {"sim-c0-s1-h0": ["fail-at", 2]},
        "api_faults": {"2": ["429:0"]},
        "watch_loss": [],
        "sabotage": {"round": 1},
    }


class TestSampling:
    def test_same_seed_same_program(self):
        assert fuzz.sample_program(7) == fuzz.sample_program(7)

    def test_programs_vary_across_seeds(self):
        drawn = [json.dumps(fuzz.sample_program(s), sort_keys=True)
                 for s in range(8)]
        assert len(set(drawn)) > 1, "eight seeds drew one program"

    def test_grammar_only(self):
        kinds = {"flap", "flap-until", "fail-at", "kubelet-down-at",
                 "torn-link"}
        for s in range(12):
            p = fuzz.sample_program(s)
            for prog in p["programs"].values():
                assert prog[0] in kinds
            for fault in p["api_faults"].values():
                assert fault == "blackout" or isinstance(fault, list)


class TestValidation:
    def test_unknown_node_rejected(self):
        prog = {"slices": 1, "rounds": 2,
                "programs": {"nope-s9-h9": ["fail-at", 1]}}
        with pytest.raises(ScenarioError, match="unknown node"):
            fuzz.run_program(prog)

    def test_unknown_program_kind_rejected(self):
        prog = {"slices": 1, "rounds": 2,
                "programs": {"sim-c0-s0-h0": ["explode", 1]}}
        with pytest.raises(ScenarioError, match="unknown failure program"):
            fuzz.run_program(prog)

    def test_bad_arity_rejected(self):
        prog = {"slices": 1, "rounds": 2,
                "programs": {"sim-c0-s0-h0": ["flap", 1]}}
        with pytest.raises(ScenarioError, match="expected 3 elements"):
            fuzz.run_program(prog)

    def test_bad_fault_rejected(self):
        prog = {"slices": 1, "rounds": 2, "programs": {},
                "api_faults": {"1": 7}}
        with pytest.raises(ScenarioError, match="api_faults"):
            fuzz.run_program(prog)


class TestCampaign:
    def test_campaign_byte_identical(self):
        a = fuzz.run_fuzz(0, 2)
        b = fuzz.run_fuzz(0, 2)
        assert fuzz.fuzz_report_json(a) == fuzz.fuzz_report_json(b)
        assert a["ok"], f"sampled seeds went red: {a['runs']}"
        assert [r["seed"] for r in a["runs"]] == [0, 1]
        assert a["reproducer"] is None


class TestShrinker:
    def test_sabotage_shrinks_deterministically_to_minimal(self):
        program = _sabotage_program()
        bad = fuzz.violated(fuzz.run_program(program))
        assert "disruption-budget" in bad, "the matrix must catch sabotage"
        shrunk, steps = fuzz.shrink(program, "disruption-budget")
        again, steps_again = fuzz.shrink(program, "disruption-budget")
        assert (shrunk, steps) == (again, steps_again), \
            "shrinking is not replayable"
        # 1-minimal: every decoy gone, the fleet halved to one slice, the
        # rounds trimmed to just enough to reach the sabotage.
        assert shrunk["programs"] == {}
        assert shrunk["api_faults"] == {}
        assert shrunk["slices"] == 1
        assert shrunk["rounds"] == program["sabotage"]["round"] + 1
        assert shrunk["sabotage"] == program["sabotage"]
        assert any(s.startswith("delete-program") for s in steps)
        assert any(s.startswith("halve-fleet") for s in steps)
        assert any(s.startswith("shorten-rounds") for s in steps)
        # The minimal reproducer still replays red — the permanence the
        # sim_reproducers/ harness relies on.
        assert "disruption-budget" in fuzz.violated(fuzz.run_program(shrunk))


class TestFuzzCli:
    def test_replay_red_reproducer_exits_3(self, capsys):
        path = os.path.join(REPRO_DIR, "over-budget-sabotage.json")
        rc = sim_cli.main(["--replay", path])
        out = capsys.readouterr().out
        assert rc == checker.EXIT_NONE_READY
        assert "disruption-budget" in out

    def test_replay_accepts_bare_program(self, tmp_path, capsys):
        bare = {"slices": 1, "rounds": 2, "programs": {}}
        path = tmp_path / "bare.json"
        path.write_text(json.dumps(bare))
        rc = sim_cli.main(["--replay", str(path), "--report", "json"])
        report = json.loads(capsys.readouterr().out)
        assert rc == checker.EXIT_OK
        assert report["ok"] is True
        assert report["scenario"] == "fuzz"

    def test_replay_missing_file_exits_1(self, capsys):
        rc = sim_cli.main(["--replay", "/nonexistent/nope.json"])
        assert rc == checker.EXIT_ERROR
        assert "Error:" in capsys.readouterr().err

    @pytest.mark.parametrize("argv", [
        ["--fuzz", "--scenario", "flap-storm"],
        ["--replay", "x.json", "--fuzz"],
        ["--replay", "x.json", "--scenario", "flap-storm"],
        ["--fuzz", "--seeds", "0"],
    ])
    def test_usage_errors(self, argv, capsys):
        with pytest.raises(SystemExit) as exc:
            sim_cli.main(argv)
        assert exc.value.code == 2
        capsys.readouterr()
