"""Pipeline-parallel (pp) and expert-parallel (ep) probe tests over the
8-device CPU mesh: numerics vs single-device references, and behavioral
properties — stage *order* matters for the pipeline, expert *identity*
matters for MoE — so a mis-routed hop or shuffle cannot pass silently."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from tpu_node_checker.parallel import (
    MeshSpec,
    build_mesh,
    make_moe_layer,
    make_pipeline,
    moe_probe,
    pipeline_probe,
    reference_moe,
    reference_pipeline,
)


class TestPipelineProbe:
    def test_matches_sequential_reference(self):
        r = pipeline_probe()
        assert r.ok, r.error
        assert r.n_stages == 8
        assert r.n_microbatches == 4
        assert r.max_abs_err < 1e-4

    def test_subset_mesh(self):
        mesh = build_mesh(MeshSpec((("pp", 4),)), jax.devices()[:4])
        r = pipeline_probe(mesh=mesh, n_microbatches=6)
        assert r.ok, r.error
        assert r.n_stages == 4

    def test_multiaxis_mesh_flattened(self):
        mesh = build_mesh(MeshSpec((("x", 2), ("y", 4))))
        r = pipeline_probe(mesh=mesh)
        assert r.ok, r.error
        assert r.n_stages == 8

    def test_fewer_microbatches_than_stages(self):
        r = pipeline_probe(n_microbatches=2)
        assert r.ok, r.error

    def test_probe_never_raises(self):
        r = pipeline_probe(d_model=0)
        assert not r.ok
        assert r.error

    def test_stage_order_matters(self):
        # The composed function must apply stage 0 first — feeding the same
        # weights reversed must change the answer (guards against a schedule
        # that happens to touch every stage but in the wrong order).
        mesh = build_mesh(MeshSpec((("pp", 8),)))
        ks = jax.random.split(jax.random.PRNGKey(7), 3)
        w = jax.random.normal(ks[0], (8, 16, 16), jnp.float32) / 4.0
        b = jax.random.normal(ks[1], (8, 16), jnp.float32) * 0.1
        x = jax.random.normal(ks[2], (2, 2, 16), jnp.float32)
        fn = make_pipeline(mesh)
        ws = NamedSharding(mesh, P("pp", None, None))
        bs = NamedSharding(mesh, P("pp", None))
        rep = NamedSharding(mesh, P())
        fwd = np.asarray(
            fn(jax.device_put(w, ws), jax.device_put(b, bs), jax.device_put(x, rep))
        )
        rev = np.asarray(
            fn(
                jax.device_put(w[::-1], ws),
                jax.device_put(b[::-1], bs),
                jax.device_put(x, rep),
            )
        )
        assert not np.allclose(fwd, rev)
        np.testing.assert_allclose(
            fwd, np.asarray(reference_pipeline(w, b, x)), atol=1e-5
        )


class TestMoEProbe:
    def test_matches_dense_reference(self):
        r = moe_probe()
        assert r.ok, r.error
        assert r.n_experts == 8
        assert r.tokens == 8 * 16
        assert r.max_abs_err < 1e-4

    def test_subset_mesh(self):
        mesh = build_mesh(MeshSpec((("ep", 4),)), jax.devices()[:4])
        r = moe_probe(mesh=mesh)
        assert r.ok, r.error
        assert r.n_experts == 4

    def test_token_count_rounds_up_to_expert_multiple(self):
        r = moe_probe(tokens_per_device=9)  # not divisible by 8 → rounded
        assert r.ok, r.error
        assert r.tokens == 8 * 16

    def test_probe_never_raises(self):
        r = moe_probe(d_model=0)
        assert not r.ok
        assert r.error

    def test_expert_identity_matters(self):
        # Permuting expert weights must change the output: tokens are routed
        # to a *specific* expert, so a corrupted all_to_all that still
        # delivers balanced loads cannot pass.
        mesh = build_mesh(MeshSpec((("ep", 8),)))
        n, T, d, f = 8, 8, 16, 32
        ks = jax.random.split(jax.random.PRNGKey(9), 4)
        w1 = jax.random.normal(ks[0], (n, d, f), jnp.float32) / 4.0
        w2 = jax.random.normal(ks[1], (n, f, d), jnp.float32) / 6.0
        wr = jax.random.normal(ks[2], (d, n), jnp.float32)
        x = jax.random.normal(ks[3], (n * T, d), jnp.float32)
        fn = make_moe_layer(mesh)
        es = NamedSharding(mesh, P("ep", None, None))
        rep = NamedSharding(mesh, P())
        ts = NamedSharding(mesh, P("ep", None))
        out = np.asarray(
            fn(
                jax.device_put(w1, es),
                jax.device_put(w2, es),
                jax.device_put(wr, rep),
                jax.device_put(x, ts),
            )
        )
        perm = np.roll(np.arange(n), 1)
        out_p = np.asarray(
            fn(
                jax.device_put(w1[perm], es),
                jax.device_put(w2[perm], es),
                jax.device_put(wr, rep),
                jax.device_put(x, ts),
            )
        )
        assert not np.allclose(out, out_p)
        np.testing.assert_allclose(
            out, np.asarray(reference_moe(w1, w2, wr, x, n)), atol=1e-5
        )
