"""Push-delta watch feed: the ``GET /api/v1/watch`` frame contract.

The wire under test (DESIGN.md §20):

* one JSON frame per request — ``delta`` (only the CHANGED entries, as
  the server's exact cached byte fragments), ``resync`` (every entry),
  or ``heartbeat`` (liveness, no entries);
* the cursor IS the collection entity's strong ETag: folding a frame
  into a cached entry table reproduces the ``/api/v1/nodes`` body
  byte-for-byte, verified against ``to``;
* a stale/evicted ``since`` gets a full-resync frame, never a 404, and
  the resync is served EXACTLY ONCE per stale reconnect — pinned
  fixture-side through :meth:`FeedState.stats`, the same way PR 6's
  relist-exactly-once test pinned the k8s watch fallback;
* named side-channel blocks (summary, remediation budget, analytics
  SLO) ride every frame, so budgets propagate at delta speed;
* 16-client hammer: concurrent feed consumers fold live publishes with
  zero torn frames while the poll surface keeps its 200/304 contract.

Wall-clock guard: same policy as tests/test_server.py — long-poll waits
are bounded by explicit ``timeout=`` windows, never real sleeps.
"""

import gzip
import http.client
import json
import threading
import time
import urllib.parse

import pytest

from tests import fixtures as fx
from tpu_node_checker.server.app import FleetStateServer
from tpu_node_checker.server.feed import FeedState
from tpu_node_checker.server.snapshot import (
    Entity,
    build_fragment,
    joined_prefix,
)

WALL_CLOCK_BUDGET_S = 20.0


@pytest.fixture(autouse=True)
def _wall_clock_guard():
    t0 = time.perf_counter()
    yield
    elapsed = time.perf_counter() - t0
    assert elapsed < WALL_CLOCK_BUDGET_S, (
        f"feed test burned {elapsed:.1f}s of wall-clock — a real sleep or "
        "a wedged long-poll leaked in"
    )


class _Round:
    def __init__(self, payload, exit_code=0):
        self.payload = payload
        self.exit_code = exit_code


def _payload(n=4, flip=(), drop=()):
    nodes = [
        {"name": f"tpu-{i:02d}", "ready": i not in flip, "accelerators": 4}
        for i in range(n)
        if i not in drop
    ]
    ready = sum(1 for nd in nodes if nd["ready"])
    return {
        "total_nodes": len(nodes), "ready_nodes": ready,
        "total_chips": len(nodes) * 4, "ready_chips": ready * 4,
        "nodes": nodes, "slices": [], "cluster": "us-a",
        "cluster_source": "flag",
        "exit_code": 0 if ready == len(nodes) else 3,
    }


def _req(port, path, headers=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    try:
        conn.request("GET", path, headers=headers or {})
        resp = conn.getresponse()
        return resp.status, dict(resp.headers.items()), resp.read()
    finally:
        conn.close()


def _watch(port, since="", timeout=None, headers=None, rev=None):
    query = {"since": since} if since else {}
    if timeout is not None:
        query["timeout"] = f"{timeout:g}"
    if rev is not None:
        query["rev"] = str(rev)
    path = "/api/v1/watch"
    if query:
        path += "?" + urllib.parse.urlencode(query)
    status, resp_headers, body = _req(port, path, headers=headers)
    frame = json.loads(body) if status == 200 else None
    return status, resp_headers, frame


def _wait_parked(server):
    """Block (bounded) until a watch request is parked in the feed's
    Condition — the deterministic 'consumer is long-polling' observation
    the wake tests need before they trigger a publish."""
    deadline = time.perf_counter() + 5.0
    while not server._feed._cond._waiters:
        assert time.perf_counter() < deadline, "consumer never parked"
        time.sleep(0.005)  # tnc: allow-test-wall-clock(bounded 5s poll observing a REAL request thread park in the feed Condition)


def _splice(frame):
    """Reproduce the collection body a frame's entries describe — the
    client-side fold's final step, relying on nothing but the frame."""
    key = frame["key"]
    frags = [build_fragment(e) for e in frame[key]]
    return joined_prefix(frame["head"], key) + b", ".join(frags) + b"]}\n"


class _Fold:
    """A minimal feed consumer: cursor + entry table, digest-verified."""

    def __init__(self):
        self.cursor = ""
        self.table = {}
        self.head = None
        self.key = "nodes"
        self.blocks = {}

    def apply(self, frame):
        kind = frame["kind"]
        assert kind in ("delta", "resync", "heartbeat"), kind
        self.blocks = frame["blocks"]
        if kind == "heartbeat":
            assert frame["to"] == frame["from"]
            return
        if kind == "resync":
            self.table = {}
        elif (frame.get("from") or "") != self.cursor:
            self.cursor = ""  # dropped frame: resync on the next request
            return
        self.key = frame["key"]
        name_key = frame["name_key"]
        for name in frame["removed"]:
            self.table.pop(name, None)
        for entry in frame[self.key]:
            self.table[entry[name_key]] = entry
        self.head = frame["head"]
        body = (
            joined_prefix(self.head, self.key)
            + b", ".join(build_fragment(e) for e in self.table.values())
            + b"]}\n"
        )
        assert Entity(body).etag == frame["to"], "folded body digest mismatch"
        self.cursor = frame["to"]
        self.body = body


# ---------------------------------------------------------------------------
# FeedState units (ring fold, blocks, lifecycle)
# ---------------------------------------------------------------------------


class TestFeedStateUnits:
    def _publish(self, fs, etag, changed, removed=(), names=None):
        frags = {n: f'{{"name": "{n}"}}'.encode() for n in (names or changed)}
        fs.publish(etag, 1, 1.0, {"count": len(frags)}, "nodes",
                   frags, None, changed, removed)

    def test_ring_eviction_resyncs_not_unbounded_delta(self):
        fs = FeedState(ring_size=3)
        self._publish(fs, '"e0"', None, names=["a"])
        for i in range(1, 6):
            self._publish(fs, f'"e{i}"', ["a"])
        # "e2" is still ringed (last 3 transitions: e2→e3→e4→e5) …
        frame = json.loads(fs.frame('"e2"', 0).raw)
        assert frame["kind"] == "delta" and frame["to"] == '"e5"'
        # … while "e0" fell off the ring: full resync, reason recorded.
        frame = json.loads(fs.frame('"e0"', 0).raw)
        assert frame["kind"] == "resync"
        assert frame["reason"] == "stale-cursor"
        assert fs.stats()[1] == {"stale-cursor": 1}

    def test_fold_cancels_changed_against_removed(self):
        fs = FeedState()
        self._publish(fs, '"e0"', None, names=["a", "b"])
        self._publish(fs, '"e1"', ["b"], names=["a", "b"])   # b changes…
        self._publish(fs, '"e2"', [], removed=["b"], names=["a"])  # …then goes
        frame = json.loads(fs.frame('"e0"', 0).raw)
        assert frame["kind"] == "delta"
        assert [e["name"] for e in frame["nodes"]] == []
        assert frame["removed"] == ["b"]

    def test_undiffable_publish_clears_feed_then_recovers(self):
        fs = FeedState()
        self._publish(fs, '"e0"', None, names=["a"])
        fs.clear()
        assert fs.frame("", 0) is None  # the handler's 503 path
        self._publish(fs, '"e1"', None, names=["a"])
        assert json.loads(fs.frame('"e0"', 0).raw)["kind"] == "resync"

    def test_blocks_merge_copy_on_write(self):
        fs = FeedState()
        frags = {"a": b'{"name": "a"}'}
        fs.publish('"e0"', 1, 1.0, {}, "nodes", frags, None, None, (),
                   blocks={"summary": {"healthy": True}})
        fs.update_blocks("remediation", {"budget": 3})
        held = json.loads(fs.frame("", 0).raw)["blocks"]
        # A later round publish carrying only the summary must not drop
        # the previously published remediation block.
        fs.publish('"e1"', 2, 2.0, {}, "nodes", frags, None, ["a"], (),
                   blocks={"summary": {"healthy": False}})
        merged = json.loads(fs.frame("", 0).raw)["blocks"]
        assert merged == {"summary": {"healthy": False},
                          "remediation": {"budget": 3}}
        assert held["summary"] == {"healthy": True}  # copy-on-write held


# ---------------------------------------------------------------------------
# The HTTP frame contract
# ---------------------------------------------------------------------------


class TestWatchFrames:
    @pytest.fixture
    def server(self):
        srv = FleetStateServer(0, host="127.0.0.1")
        yield srv
        srv.close()

    def test_first_request_resyncs_byte_identical(self, server):
        server.publish(_Round(_payload()))
        _, headers, nodes_body = _req(server.port, "/api/v1/nodes")
        status, _, frame = _watch(server.port)
        assert status == 200
        assert frame["kind"] == "resync" and frame["reason"] == "requested"
        assert frame["from"] is None
        assert frame["to"] == headers["ETag"]
        assert frame["name_key"] == "name"
        # The frame's entries splice back into the EXACT collection body —
        # the byte-identity the cursor (the entity's own ETag) certifies.
        assert _splice(frame) == nodes_body
        assert frame["blocks"]["summary"]["total_nodes"] == 4

    def test_delta_carries_only_changed_entries(self, server):
        server.publish(_Round(_payload()))
        fold = _Fold()
        fold.apply(_watch(server.port)[2])
        payload = _payload(flip={1})
        server.publish(_Round(payload, payload["exit_code"]))
        status, _, frame = _watch(server.port, since=fold.cursor)
        assert status == 200
        assert frame["kind"] == "delta" and frame["from"] == fold.cursor
        assert [e["name"] for e in frame["nodes"]] == ["tpu-01"]
        assert frame["removed"] == []
        fold.apply(frame)  # digest-verifies the folded body against `to`
        assert fold.body == _req(server.port, "/api/v1/nodes")[2]

    def test_removed_node_is_named_not_reencoded(self, server):
        server.publish(_Round(_payload()))
        fold = _Fold()
        fold.apply(_watch(server.port)[2])
        server.publish(_Round(_payload(drop={3})))
        _, _, frame = _watch(server.port, since=fold.cursor)
        assert frame["kind"] == "delta"
        assert frame["removed"] == ["tpu-03"]
        assert [e["name"] for e in frame["nodes"]] == []
        fold.apply(frame)
        assert fold.body == _req(server.port, "/api/v1/nodes")[2]

    def test_stale_cursor_resyncs_exactly_once_never_404(self, server):
        """Satellite 2: the resync-exactly-once contract.  A consumer
        reconnecting with an evicted/unknown cursor pays ONE full-resync
        frame — never a 404 — and rides deltas from there on."""
        server.publish(_Round(_payload()))
        status, _, frame = _watch(server.port, since='"cursor-from-a-past-life"')
        assert status == 200, "a stale cursor must never 404"
        assert frame["kind"] == "resync" and frame["reason"] == "stale-cursor"
        fold = _Fold()
        fold.apply(frame)
        # Fixture-side pin (the FeedState.stats seam): exactly one resync.
        assert server._feed.stats()[1] == {"stale-cursor": 1}
        server.publish(_Round(_payload(flip={0})))
        _, _, frame = _watch(server.port, since=fold.cursor)
        assert frame["kind"] == "delta"  # resumed on deltas, no second resync
        assert server._feed.stats()[1] == {"stale-cursor": 1}

    def test_heartbeat_on_quiet_window(self, server):
        server.publish(_Round(_payload()))
        cursor = _watch(server.port)[2]["to"]
        status, _, frame = _watch(server.port, since=cursor, timeout=0.05)
        assert status == 200
        assert frame["kind"] == "heartbeat"
        assert frame["from"] == cursor and frame["to"] == cursor
        assert frame["nodes"] == []
        assert frame["blocks"]["summary"]["total_nodes"] == 4

    def test_long_poll_wakes_on_publish(self, server):
        server.publish(_Round(_payload()))
        cursor = _watch(server.port)[2]["to"]
        got = {}
        parked = threading.Event()

        def consumer():
            parked.set()
            got["frame"] = _watch(server.port, since=cursor, timeout=10)[2]

        t = threading.Thread(target=consumer, name="tnc-test-feed-consumer",
                             daemon=True)
        t.start()
        parked.wait(timeout=10)
        _wait_parked(server)
        server.publish(_Round(_payload(flip={2})))
        t.join(timeout=10)
        assert not t.is_alive(), "long-poll never woke on publish"
        assert got["frame"]["kind"] == "delta"
        assert [e["name"] for e in got["frame"]["nodes"]] == ["tpu-02"]

    def test_budget_and_slo_blocks_ride_at_delta_speed(self, server):
        """The remediation lease budget (PR 11) and analytics SLO doc
        (PR 15) propagate between publishes as named blocks — a parked
        consumer wakes on the block update alone (from == to, no
        entries)."""
        server.publish(_Round(_payload()))
        cursor = _watch(server.port)[2]["to"]
        got = {}
        parked = threading.Event()

        def consumer():
            parked.set()
            got["frame"] = _watch(server.port, since=cursor, timeout=10)[2]

        t = threading.Thread(target=consumer, name="tnc-test-feed-blocks",
                             daemon=True)
        t.start()
        parked.wait(timeout=10)
        _wait_parked(server)
        server.publish_remediation({"budget": {"max_per_round": 2}})
        t.join(timeout=10)
        assert not t.is_alive(), "block update never woke the consumer"
        frame = got["frame"]
        # A blocks-only wake: from == to (the collection never moved), no
        # entries, just the named block — lease arithmetic at frame speed.
        assert frame["kind"] == "delta"
        assert frame["from"] == cursor and frame["to"] == cursor
        assert frame["nodes"] == []
        assert frame["blocks"]["remediation"] == {
            "budget": {"max_per_round": 2}
        }
        # Blocks ride EVERY frame: a late-arriving consumer sees the SLO
        # doc (and the withdrawn budget) on its next heartbeat, no park
        # choreography needed.
        server.publish_analytics({"slo": {"ready_p50": 0.99}})
        _, _, frame = _watch(server.port, since=cursor, timeout=0.05)
        assert frame["blocks"]["analytics_slo"] == {"ready_p50": 0.99}
        server.publish_remediation(None)
        _, _, frame = _watch(server.port, since=cursor, timeout=0.05)
        assert "remediation" not in frame["blocks"]

    def test_stale_rev_answers_immediately_never_parks(self, server):
        """A consumer that was BETWEEN polls when a blocks-only update
        fired must not sit out a long-poll window to learn about it: its
        next poll echoes the rev of its last frame, the server sees the
        mismatch, and answers an immediate entry-less heartbeat carrying
        the current blocks — blocks stay at delta speed on BOTH sides of
        the park."""
        server.publish(_Round(_payload()))
        first = _watch(server.port)[2]
        cursor, rev = first["to"], first["rev"]
        # Current rev + current cursor still parks (tiny window → heartbeat).
        _, _, frame = _watch(server.port, since=cursor, timeout=0.05, rev=rev)
        assert frame["kind"] == "heartbeat" and frame["rev"] == rev
        server.publish_analytics({"slo": {"ready_p50": 0.5}})
        t0 = time.monotonic()
        _, _, frame = _watch(server.port, since=cursor, timeout=20, rev=rev)
        assert time.monotonic() - t0 < 5.0, "stale-rev poll parked"
        assert frame["kind"] == "heartbeat"
        assert frame["from"] == cursor and frame["to"] == cursor
        assert frame["nodes"] == []
        assert frame["blocks"]["analytics_slo"] == {"ready_p50": 0.5}
        assert frame["rev"] > rev

    def test_bad_rev_param_is_a_400(self, server):
        server.publish(_Round(_payload()))
        cursor = _watch(server.port)[2]["to"]
        status, _, _ = _watch(server.port, since=cursor, timeout=0.05,
                              rev="new")
        assert status == 400

    def test_gzip_negotiated_frame_decompresses_identical(self, server):
        server.publish(_Round(_payload(n=64)))
        status, headers, raw = _req(server.port, "/api/v1/watch")
        status, gz_headers, gz_body = _req(
            server.port, "/api/v1/watch",
            headers={"Accept-Encoding": "gzip"},
        )
        assert gz_headers.get("Content-Encoding") == "gzip"
        assert gzip.decompress(gz_body) == raw

    def test_watch_before_first_round_is_503(self, server):
        status, _, body = _req(server.port, "/api/v1/watch")
        assert status == 503
        assert json.loads(body)["ready"] is False

    def test_bad_timeout_is_400(self, server):
        server.publish(_Round(_payload()))
        status, _, body = _req(server.port, "/api/v1/watch?timeout=soon")
        assert status == 400
        assert b"timeout" in body

    def test_feed_disabled_is_404(self):
        srv = FleetStateServer(0, host="127.0.0.1", feed=False)
        try:
            srv.publish(_Round(_payload()))
            status, _, body = _req(srv.port, "/api/v1/watch")
            assert status == 404  # no feed → no route; never a hung poll
        finally:
            srv.close()

    def test_server_close_releases_parked_consumers(self, server):
        server.publish(_Round(_payload()))
        cursor = _watch(server.port)[2]["to"]
        results = []
        parked = threading.Event()

        def consumer():
            parked.set()
            try:
                results.append(_watch(server.port, since=cursor, timeout=25))
            except (OSError, http.client.HTTPException):
                results.append(("torn", None, None))

        t = threading.Thread(target=consumer, name="tnc-test-feed-close",
                             daemon=True)
        t.start()
        parked.wait(timeout=10)
        _wait_parked(server)
        server.close()
        t.join(timeout=10)
        assert not t.is_alive(), "close left a consumer parked"


# ---------------------------------------------------------------------------
# Feed lifecycle under the 16-client hammer (satellite 3)
# ---------------------------------------------------------------------------


class TestFeedUnderHammer:
    def test_concurrent_consumers_fold_live_publishes_untorn(self):
        """16 poll clients + 4 feed consumers against 30 live publishes:
        every frame parses, every fold digest-verifies against ``to`` (no
        torn reads), and the poll surface keeps its 200/304 bijection."""
        srv = FleetStateServer(0, host="127.0.0.1")
        srv.publish(_Round(_payload(n=32)))
        stop = threading.Event()
        folds = [_Fold() for _ in range(4)]
        consumer_errors = []
        frames_seen = [0] * len(folds)

        def consume(slot):
            fold = folds[slot]
            try:
                while not stop.is_set():
                    status, _, frame = _watch(
                        srv.port, since=fold.cursor, timeout=0.2
                    )
                    assert status == 200, status
                    fold.apply(frame)  # parses + digest-verifies every frame
                    frames_seen[slot] += 1
            except Exception as exc:  # noqa: BLE001 — surfaced as a failure below
                consumer_errors.append(f"consumer {slot}: {exc!r}")

        consumers = [
            threading.Thread(target=consume, args=(i,),
                             name=f"tnc-test-feed-hammer-{i}", daemon=True)
            for i in range(len(folds))
        ]
        for t in consumers:
            t.start()

        # Seeded churn plan (sim load generation): ~3 nodes flip per
        # round, replayable by seed if a torn frame ever surfaces.
        churn_plan = fx.churn_flips(seed=16, nodes=32, rounds=30,
                                    fraction=0.1)

        def swaps():
            for flips in churn_plan:
                srv.publish(_Round(_payload(n=32, flip=flips)))

        try:
            flat = fx.hammer_fleet_api(
                srv.port, ["/api/v1/nodes", "/api/v1/summary"], swaps,
                clients=16,
            )
            stop.set()
            for t in consumers:
                t.join(timeout=10)
                assert not t.is_alive(), "feed consumer wedged"
            assert not consumer_errors, consumer_errors
            fx.assert_poll_contract(flat)
            final_body = _req(srv.port, "/api/v1/nodes")[2]
            final_etag = Entity(final_body).etag
            for slot, fold in enumerate(folds):
                assert frames_seen[slot] > 0, f"consumer {slot} starved"
                # Drain to head: at most one resync (if a frame was
                # dropped mid-churn), then byte-identity with the final
                # polled body.
                while fold.cursor != final_etag:
                    status, _, frame = _watch(
                        srv.port, since=fold.cursor, timeout=0.05
                    )
                    fold.apply(frame)
                    if frame["kind"] == "heartbeat":
                        break
                assert fold.cursor == final_etag
                assert fold.body == final_body
        finally:
            stop.set()
            srv.close()
