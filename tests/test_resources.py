"""Registry + quantity parsing unit tests (reference keys: check-gpu-node.py:39-44)."""

from tpu_node_checker.resources import KeyMatcher, ResourceRegistry, default_registry
from tpu_node_checker.utils.quantity import parse_quantity


class TestRegistry:
    def test_reference_gpu_keys_all_match(self):
        reg = default_registry()
        for key in ("nvidia.com/gpu", "amd.com/gpu", "gpu.intel.com/i915", "intel.com/gpu"):
            m = reg.match(key)
            assert m is not None and m.family == "gpu"

    def test_tpu_keys_match(self):
        reg = default_registry()
        assert reg.match("google.com/tpu").family == "tpu"
        for key in ("cloud-tpus.google.com/v4", "cloud-tpus.google.com/v5e",
                    "cloud-tpus.google.com/v5p", "cloud-tpus.google.com/v6e"):
            m = reg.match(key)
            assert m is not None and m.family == "tpu"

    def test_non_accelerator_keys_do_not_match(self):
        reg = default_registry()
        for key in ("cpu", "memory", "pods", "ephemeral-storage",
                    "cloud-tpus.google.com", "example.com/tpu"):
            assert reg.match(key) is None

    def test_scan_breakdown_and_families(self):
        reg = default_registry()
        matches = reg.scan({"cpu": "8", "nvidia.com/gpu": "2", "google.com/tpu": "4"})
        got = {m.key: (m.count, m.family) for m in matches}
        assert got == {"nvidia.com/gpu": (2, "gpu"), "google.com/tpu": (4, "tpu")}

    def test_scan_drops_zero_and_garbage(self):
        reg = default_registry()
        assert reg.scan({"nvidia.com/gpu": "0"}) == []
        assert reg.scan({"nvidia.com/gpu": "banana"}) == []
        assert reg.scan(None) == []
        assert reg.scan({}) == []

    def test_with_extra_keys(self):
        reg = default_registry().with_extra_keys(["habana.ai/gaudi"])
        assert reg.match("habana.ai/gaudi").family == "gpu"

    def test_exact_matcher_is_not_glob(self):
        m = KeyMatcher("google.com/tpu", "tpu", "google")
        assert not m.matches("google.com/tpux")

    def test_first_match_wins_order(self):
        reg = ResourceRegistry([KeyMatcher("a/*", "gpu", "x"), KeyMatcher("a/b", "tpu", "y")])
        assert reg.match("a/b").family == "gpu"


class TestQuantity:
    def test_plain_ints(self):
        assert parse_quantity("4") == 4
        assert parse_quantity(8) == 8
        assert parse_quantity("256") == 256

    def test_suffixes(self):
        assert parse_quantity("1Ki") == 1024
        assert parse_quantity("2k") == 2000
        assert parse_quantity("500m") == 0  # half a device floors to zero

    def test_garbage(self):
        assert parse_quantity("") is None
        assert parse_quantity(None) is None
        assert parse_quantity("NaNGi") is None
        assert parse_quantity(True) is None
