"""Formatter tests: table, JSON schema (reference superset), Slack mrkdwn."""

import json

from tests import fixtures as fx
from tpu_node_checker import report
from tpu_node_checker.detect import group_slices, select_accelerator_nodes


def _analyzed(nodes):
    accel, ready = select_accelerator_nodes(nodes)
    return accel, ready, group_slices(accel)


class TestTable:
    def test_empty_message(self):
        # Mirrors check-gpu-node.py:230-232.
        assert "No accelerator nodes" in report.format_node_table([])

    def test_columns_present(self):
        accel, _, _ = _analyzed(fx.tpu_v5e_single_host())
        table = report.format_node_table(accel)
        assert "gke-tpu-v5e-0" in table
        assert "google.com/tpu:8" in table
        assert "tpu-v5-lite-podslice 2x4" in table

    def test_notready_rendered(self):
        accel, _, _ = _analyzed(fx.gpu_pool(1, ready=False))
        assert "NotReady" in report.format_node_table(accel)

    def test_slice_table(self):
        accel, _, slices = _analyzed(fx.tpu_v5p_64_slice(not_ready=1))
        table = report.format_slice_table(slices)
        assert "v5p-pool" in table
        assert "15/16" in table
        assert "60/64" in table
        assert "DEGRADED" in table


class TestJsonPayload:
    def test_reference_schema_superset(self):
        # Reference payload keys (check-gpu-node.py:273-279) must all exist.
        accel, ready, slices = _analyzed(fx.gpu_pool(2))
        payload = report.build_json_payload(accel, ready, slices)
        assert payload["total_nodes"] == 2
        assert payload["ready_nodes"] == 2
        node = payload["nodes"][0]
        for key in ("name", "ready", "gpus", "gpu_breakdown", "labels", "taints"):
            assert key in node
        assert node["gpus"] == 1
        assert node["gpu_breakdown"] == {"nvidia.com/gpu": 1}

    def test_tpu_fields(self):
        accel, ready, slices = _analyzed(fx.tpu_v5e_256_slice())
        payload = report.build_json_payload(accel, ready, slices)
        assert payload["total_chips"] == 256
        assert payload["ready_chips"] == 256
        assert payload["slices"][0]["expected_chips"] == 256
        assert payload["slices"][0]["complete"] is True

    def test_round_trips_through_json(self):
        accel, ready, slices = _analyzed(fx.mixed_cluster_one_notready())
        payload = report.build_json_payload(accel, ready, slices, timings_ms={"total": 1.0})
        assert json.loads(report.dumps(payload)) == payload


class TestSlackMessage:
    def test_tri_state_headers(self):
        # check-gpu-node.py:116-124 tri-state.
        accel, ready, slices = _analyzed(fx.tpu_v5e_single_host())
        assert report.format_slack_message(accel, ready, slices).startswith("✅")

        accel, ready, slices = _analyzed(fx.gpu_pool(2, ready=False))
        assert report.format_slack_message(accel, ready, slices).startswith("⚠️")

        assert report.format_slack_message([], [], []).startswith("❌")

    def test_node_bullets(self):
        accel, ready, slices = _analyzed(fx.gpu_pool(1))
        msg = report.format_slack_message(accel, ready, slices)
        assert "• `gke-gpu-pool-0`: Ready, devices: 1 (nvidia.com/gpu:1)" in msg

    def test_slice_line_degraded(self):
        accel, ready, slices = _analyzed(fx.tpu_v5p_64_slice(not_ready=2))
        msg = report.format_slack_message(accel, ready, slices)
        assert "56/64 chips, DEGRADED" in msg

    def test_probe_failed_bullet_names_the_reason(self):
        # "Failed HOW" is the first question on an alert: the bullet carries
        # the (truncated) probe error, not just a generic FAILED.
        accel, ready, slices = _analyzed(fx.tpu_v5e_single_host())
        accel[0].probe = {
            "ok": False,
            "level": "compute",
            "error": "perf_floor: matmul_tflops 19.7 <\nfloor 78.8 " + "x" * 200,
        }
        ready = [n for n in accel if n.effectively_ready]
        msg = report.format_slack_message(accel, ready, slices, healthy=False)
        assert "chip probe FAILED (perf_floor: matmul_tflops 19.7 < floor" in msg
        assert "…" in msg  # long errors truncate visibly
        assert "x" * 121 not in msg
        # Newlines in traceback tails are collapsed — a bullet must stay
        # one Slack line.
        bullet = [l for l in msg.splitlines() if "chip probe FAILED" in l][0]
        assert "floor 78.8" in bullet

    def test_large_fleet_lists_only_problem_nodes(self):
        # 64 hosts, 2 NotReady: exhaustive bullets would bury the signal
        # (and push Slack's limits); only the sick hosts are listed.
        accel, ready, slices = _analyzed(fx.tpu_v5e_256_slice(not_ready=2))
        msg = report.format_slack_message(accel, ready, slices)
        assert "`gke-tpu-v5e256-000`" in msg  # NotReady host listed
        assert "`gke-tpu-v5e256-001`" in msg
        assert "`gke-tpu-v5e256-002`" not in msg  # healthy host omitted
        assert "… 62 healthy nodes omitted" in msg

    def test_mass_outage_caps_problem_list(self):
        # All 64 hosts down: the message lists 30 and summarizes the rest.
        accel, ready, slices = _analyzed(fx.tpu_v5e_256_slice(not_ready=64))
        msg = report.format_slack_message(accel, ready, slices)
        assert msg.count("• `gke-tpu-v5e256-") == 30
        assert "… 34 more problem nodes omitted" in msg
        assert "healthy nodes omitted" not in msg

    def test_small_cluster_keeps_exhaustive_bullets(self):
        # ≤20 nodes: reference behavior — every node listed, no omission line.
        accel, ready, slices = _analyzed(fx.gpu_pool(3))
        msg = report.format_slack_message(accel, ready, slices)
        assert msg.count("• `gke-gpu-pool-") == 3
        assert "omitted" not in msg

    def test_many_slices_list_only_degraded(self):
        # A pool of many single-host slices: only the degraded ones get
        # bullets, same scaling policy as the node list.
        nodes = [
            fx.make_node(
                f"tpu-solo-{i:02d}",
                ready=i != 3,
                allocatable={"google.com/tpu": "4"},
                labels={
                    "cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-device",
                    "cloud.google.com/gke-tpu-topology": "2x2",
                    "cloud.google.com/gke-nodepool": "solo",
                },
            )
            for i in range(16)
        ]
        accel, ready, slices = _analyzed(nodes)
        assert len(slices) == 16  # one slice per host (topology fits on one)
        msg = report.format_slack_message(accel, ready, slices)
        assert msg.count("• slice ") == 1  # only the degraded one
        assert "… 15 complete slices omitted" in msg


class TestSlackQuarantineLines:
    def test_cordon_actions_surface(self):
        accel, ready, slices = _analyzed(fx.tpu_v5p_64_slice())
        msg = report.format_slack_message(
            accel, ready, slices, healthy=False,
            cordon={
                "dry_run": False,
                "cordoned": ["gke-tpu-v5p-3"],
                "failed": [],
                "skipped_over_cap": ["gke-tpu-v5p-4", "gke-tpu-v5p-5"],
            },
        )
        assert "🚧 auto-cordoned (chip probe failed): `gke-tpu-v5p-3`" in msg
        assert (
            "⚠️ cordon budget exhausted — left alone: `gke-tpu-v5p-4`, `gke-tpu-v5p-5`"
            in msg
        )

    def test_dry_run_prefix_and_uncordon(self):
        accel, ready, slices = _analyzed(fx.tpu_v5p_64_slice())
        msg = report.format_slack_message(
            accel, ready, slices, healthy=True,
            cordon={"dry_run": True, "cordoned": ["a"], "skipped_over_cap": []},
            uncordon={"dry_run": False, "uncordoned": ["b"], "failed": []},
        )
        assert "[dry-run] would auto-cordon (chip probe failed): `a`" in msg
        assert "♻️ uncordoned (probe recovered): `b`" in msg

    def test_patch_failures_surface_as_worst_state(self):
        # A known-bad node the PATCH could not cordon is STILL accepting
        # workloads — it must not hide in stderr/JSON.
        accel, ready, slices = _analyzed(fx.tpu_v5p_64_slice())
        msg = report.format_slack_message(
            accel, ready, slices, healthy=False,
            cordon={
                "dry_run": False,
                "cordoned": [],
                "skipped_over_cap": [],
                "failed": [{"node": "tpu-sick", "error": "403 forbidden"}],
            },
            uncordon={
                "dry_run": False,
                "uncordoned": [],
                "failed": [{"node": "tpu-held", "error": "timeout"}],
            },
        )
        assert "❌ cordon FAILED — still schedulable: `tpu-sick`" in msg
        assert "⚠️ uncordon failed — capacity still quarantined: `tpu-held`" in msg

    def test_empty_reports_add_no_lines(self):
        accel, ready, slices = _analyzed(fx.tpu_v5p_64_slice())
        base = report.format_slack_message(accel, ready, slices)
        with_empty = report.format_slack_message(
            accel, ready, slices,
            cordon={"dry_run": False, "cordoned": [], "skipped_over_cap": []},
            uncordon={"dry_run": False, "uncordoned": []},
        )
        assert with_empty == base

    def test_long_name_lists_capped(self):
        accel, ready, slices = _analyzed(fx.tpu_v5p_64_slice())
        msg = report.format_slack_message(
            accel, ready, slices,
            cordon={
                "dry_run": False,
                "cordoned": [f"node-{i:02d}" for i in range(14)],
                "skipped_over_cap": [],
            },
        )
        assert "`node-09`" in msg
        assert "`node-10`" not in msg
        assert "(+4 more)" in msg
