"""Serving at scale: the SO_REUSEPORT worker pool, the fast path, the
write-path token bucket, and stale-while-revalidate trend serving.

The contract under test, layer by layer:

* the **fast path** (prebuilt wire responses keyed on request-line bytes)
  answers exactly what the routed path would — status, body, ETag,
  negotiation headers — for every GET shape it claims (plain / gzip /
  If-None-Match), and everything else falls through to the routed stack;
* the **worker pool** serves one port from N accept loops, survives
  rolling worker restarts under a reconnecting hammer with nothing but
  200/304 on completed exchanges, falls back to a single listener where
  ``SO_REUSEPORT`` is missing, and sheds connections over the per-worker
  cap with a fast 503 instead of pinning handler threads;
* the **token bucket** refuses authenticated writes over ``--write-rps``
  with 429 + a ``Retry-After`` that round-trips through
  ``utils/retry.parse_retry_after`` (fake clock, zero real sleeps);
* **SWR trend serving** hands a reader the stale entity the instant the
  signature moves and rebuilds exactly once per change, off-thread.

Same wall-clock policy as tests/test_server.py: waits are bounded polls on
REAL cross-thread effects, never pacing sleeps, and every test is timed.
"""

import gzip
import http.client
import json
import socket
import threading
import time

import pytest

from tests import fixtures as fx
from tpu_node_checker import checker, cli
from tpu_node_checker.server import workers as workers_mod
from tpu_node_checker.server.app import FleetStateServer
from tpu_node_checker.server.ratelimit import TokenBucket, retry_after_header
from tpu_node_checker.server.snapshot import (
    TrendCache,
    build_snapshot,
    build_snapshot_delta,
)
from tpu_node_checker.utils.retry import parse_retry_after

WALL_CLOCK_BUDGET_S = 20.0


@pytest.fixture(autouse=True)
def _wall_clock_guard():
    t0 = time.perf_counter()
    yield
    elapsed = time.perf_counter() - t0
    assert elapsed < WALL_CLOCK_BUDGET_S, (
        f"serve-scale test burned {elapsed:.1f}s of wall-clock — a real "
        "sleep or a wedged handler leaked in"
    )


def _result(nodes=None):
    args = cli.parse_args(["--json"])
    return checker.run_check(
        args,
        nodes=[json.loads(json.dumps(n))
               for n in (nodes or fx.tpu_v5e_256_slice())],
    )


def _req(port, method, path, headers=None, body=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    try:
        conn.request(method, path, body=body, headers=headers or {})
        resp = conn.getresponse()
        return resp.status, dict(resp.headers.items()), resp.read()
    finally:
        conn.close()


# ---------------------------------------------------------------------------
# Fast path ≡ routed path
# ---------------------------------------------------------------------------


class TestFastPathParity:
    PARITY_HEADERS = ("ETag", "Content-Type", "Content-Length", "Vary",
                      "Cache-Control", "Content-Encoding")

    def _server(self):
        srv = FleetStateServer(0, host="127.0.0.1")
        srv.publish(_result())
        return srv

    def _pair(self, srv, path, headers):
        """The same GET through both stacks: the bare path rides the fast
        table; a query string misses the request-line key and rides the
        routed fallback into the identical entity."""
        fast = _req(srv.port, "GET", path, headers)
        routed = _req(srv.port, "GET", path + "?routed=1", headers)
        return fast, routed

    @pytest.mark.parametrize("path", ["/api/v1/summary", "/api/v1/nodes",
                                      "/api/v1/slices"])
    def test_plain_get_parity(self, path):
        srv = self._server()
        try:
            assert srv.fast_routes, "publish built no fast table"
            (fs, fh, fb), (rs, rh, rb) = self._pair(srv, path, {})
            assert (fs, fb) == (rs, rb)
            for key in self.PARITY_HEADERS:
                assert fh.get(key) == rh.get(key), key
        finally:
            srv.close()

    def test_gzip_and_304_parity(self):
        srv = self._server()
        try:
            gz_headers = {"Accept-Encoding": "gzip, br"}
            (fs, fh, fb), (rs, rh, rb) = self._pair(
                srv, "/api/v1/nodes", gz_headers
            )
            assert fs == rs == 200
            assert fh["Content-Encoding"] == rh["Content-Encoding"] == "gzip"
            assert gzip.decompress(fb) == gzip.decompress(rb)
            etag = fh["ETag"]
            for headers in (
                {"If-None-Match": etag},
                {"If-None-Match": f'"nope", {etag}'},  # list form
                {"If-None-Match": f"W/{etag}"},        # weak compare
                {"If-None-Match": "*"},
            ):
                (fs, fh, fb), (rs, _, _) = self._pair(
                    srv, "/api/v1/nodes", headers
                )
                assert fs == rs == 304, headers
                assert fb == b"" and fh["ETag"] == etag
        finally:
            srv.close()

    def test_non_fast_shapes_fall_through(self):
        srv = self._server()
        try:
            # HEAD rides the routed stack but keeps the GET's headers.
            g = _req(srv.port, "GET", "/api/v1/summary")
            h = _req(srv.port, "HEAD", "/api/v1/summary")
            assert h[0] == 200 and h[2] == b""
            assert h[1]["Content-Length"] == str(len(g[2]))
            assert h[1]["ETag"] == g[1]["ETag"]
            # Unknown path / wrong method keep the routed answers.
            assert _req(srv.port, "GET", "/api/v2/summary")[0] == 404
            status, headers, _ = _req(srv.port, "POST", "/api/v1/summary")
            assert status == 405 and "GET" in headers["Allow"]
        finally:
            srv.close()

    def test_malformed_and_oversized_requests_are_bounded(self):
        srv = self._server()
        try:
            with socket.create_connection(("127.0.0.1", srv.port), timeout=10) as s:
                s.sendall(b"NONSENSE\r\n\r\n")
                assert s.recv(1024).startswith(b"HTTP/1.1 400 ")
            with socket.create_connection(("127.0.0.1", srv.port), timeout=10) as s:
                s.sendall(b"GET / HTTP/1.1\r\nX-Pad: " + b"x" * 70000)
                assert s.recv(1024).startswith(b"HTTP/1.1 431 ")
        finally:
            srv.close()

    def test_pipelined_requests_batch_on_one_connection(self):
        srv = self._server()
        try:
            etag = _req(srv.port, "GET", "/api/v1/summary")[1]["ETag"]
            req = (
                "GET /api/v1/summary HTTP/1.1\r\nHost: x\r\n"
                f"If-None-Match: {etag}\r\n\r\n"
            ).encode()
            with socket.create_connection(("127.0.0.1", srv.port), timeout=10) as s:
                s.sendall(req * 50)
                got = b""
                while got.count(b"HTTP/1.1 304") < 50:
                    data = s.recv(1 << 20)
                    assert data, "server closed mid-pipeline"
                    got += data
            # The batch landed in requests_total in one merge.
            _, _, body = _req(srv.port, "GET", "/metrics")
            assert (
                'tpu_node_checker_api_server_requests_total{method="GET",'
                'route="/api/v1/summary",status="304"}' in body.decode()
            )
        finally:
            srv.close()


# ---------------------------------------------------------------------------
# Worker pool: multi-worker serving, rolling restarts, fallback, shedding
# ---------------------------------------------------------------------------


class TestWorkerPool:
    def test_multi_worker_shares_one_port(self):
        srv = FleetStateServer(0, host="127.0.0.1", workers=2)
        try:
            assert srv.workers_active == 2 and srv.reuseport
            srv.publish(_result())
            # Many fresh connections: the kernel spreads them over both
            # accept loops; every one answers the same round.
            for _ in range(8):
                status, _, body = _req(srv.port, "GET", "/api/v1/summary")
                assert status == 200 and json.loads(body)["round"] == 1
            _, _, body = _req(srv.port, "GET", "/metrics")
            assert "tpu_node_checker_api_server_workers 2.0" in body.decode()
        finally:
            srv.close()

    def test_hammer_bijection_across_worker_restarts(self):
        # The acceptance-shape hammer: reconnecting pollers see ONLY
        # 200/304 on completed exchanges while rounds publish and workers
        # roll one at a time underneath.
        srv = FleetStateServer(0, host="127.0.0.1", workers=2)
        result = _result()
        srv.publish(result)
        try:
            def swaps():
                for i in range(6):
                    srv.publish(result)
                    srv.restart_worker(i % srv.workers_active)

            flat = fx.hammer_fleet_api(
                srv.port, ("/api/v1/summary", "/api/v1/nodes"), swaps,
                clients=8, reconnect=True,
                thread_prefix="tnc-test-restart-hammer",
            )
            rounds_seen = fx.assert_poll_contract(flat)
            assert rounds_seen  # completed 200s were actually observed
            assert srv.workers_active == 2  # every restart re-filled the pool
        finally:
            srv.close()

    def test_single_listener_fallback_without_reuseport(self, monkeypatch):
        monkeypatch.setattr(workers_mod, "reuseport_available", lambda: False)
        srv = FleetStateServer(0, host="127.0.0.1", workers=4)
        try:
            assert srv.workers_active == 1 and not srv.reuseport
            srv.publish(_result())
            assert _req(srv.port, "GET", "/api/v1/summary")[0] == 200
        finally:
            srv.close()

    def test_slow_loris_pool_is_shed_not_seated(self):
        # Two idle connections fill the per-worker cap; the third is
        # answered 503 straight from the accept loop.  Freeing a slot
        # seats new connections again.
        srv = FleetStateServer(0, host="127.0.0.1", max_connections=2)
        srv.publish(_result())
        try:
            loris = [
                socket.create_connection(("127.0.0.1", srv.port), timeout=10)
                for _ in range(2)
            ]
            status, headers, _ = _req(srv.port, "GET", "/api/v1/summary")
            assert status == 503
            assert headers.get("Connection") == "close"
            assert headers.get("Retry-After")
            loris[0].close()
            deadline = time.monotonic() + 10
            status = None
            while time.monotonic() < deadline:
                status = _req(srv.port, "GET", "/api/v1/summary")[0]
                if status == 200:
                    break
                time.sleep(0.01)  # tnc: allow-test-wall-clock(bounded 10s poll for the REAL handler thread to notice the closed socket and free its slot)
            assert status == 200
            loris[1].close()
        finally:
            srv.close()


# ---------------------------------------------------------------------------
# Write-path token bucket
# ---------------------------------------------------------------------------


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


class TestTokenBucket:
    def test_burst_then_refill(self):
        clock = FakeClock()
        bucket = TokenBucket(rate=2.0, burst=3.0, monotonic=clock)
        assert [bucket.try_acquire() for _ in range(3)] == [0.0, 0.0, 0.0]
        wait = bucket.try_acquire()
        assert wait == pytest.approx(0.5)  # 1 token at 2/s
        clock.t += wait
        assert bucket.try_acquire() == 0.0
        # Refill caps at burst: a long quiet spell buys burst, not more.
        clock.t += 3600.0
        assert [bucket.try_acquire() for _ in range(3)] == [0.0, 0.0, 0.0]
        assert bucket.try_acquire() > 0.0

    def test_default_burst_floors_at_one(self):
        clock = FakeClock()
        bucket = TokenBucket(rate=0.1, monotonic=clock)
        assert bucket.burst == 1.0
        assert bucket.try_acquire() == 0.0
        assert bucket.try_acquire() == pytest.approx(10.0)

    def test_rate_must_be_positive(self):
        with pytest.raises(ValueError):
            TokenBucket(rate=0.0)

    def test_retry_after_round_trips_through_the_retry_parser(self):
        # The 429's Retry-After must be parseable by the SAME parser the
        # checker's retry ladder uses, and honoring it must always find a
        # token: ceil + floor-at-1 ≥ the true wait.
        for wait in (0.05, 0.5, 1.0, 1.2, 7.9):
            header = retry_after_header(wait)
            parsed = parse_retry_after(header)
            assert parsed is not None and parsed >= wait
            assert parsed == float(int(header))  # delta-seconds form


class TestWriteRateLimitEndToEnd:
    def _server(self, limiter):
        calls = []

        def control(name, action, dry_run, node, snap):
            calls.append((name, action))
            return 200, {"applied": True}

        srv = FleetStateServer(
            0, host="127.0.0.1", token="s3cret", control=control,
            write_limiter=limiter,
        )
        srv.publish(_result())
        return srv, calls

    def test_429_with_retry_after_then_recovery(self):
        clock = FakeClock()
        srv, calls = self._server(
            TokenBucket(rate=1.0, burst=2.0, monotonic=clock)
        )
        node = json.loads(
            _req(srv.port, "GET", "/api/v1/nodes")[2]
        )["nodes"][0]["name"]
        auth = {"Authorization": "Bearer s3cret"}
        path = f"/api/v1/nodes/{node}/cordon"
        try:
            assert _req(srv.port, "POST", path, auth)[0] == 200
            assert _req(srv.port, "POST", path, auth)[0] == 200
            status, headers, body = _req(srv.port, "POST", path, auth)
            assert status == 429
            assert len(calls) == 2  # the refused request never reached control
            wait = parse_retry_after(headers["Retry-After"])
            assert wait is not None and wait >= 1
            doc = json.loads(body)
            assert doc["node"] == node and "rate limit" in doc["error"]
            # Honoring the header finds a token (fake clock, no sleeping).
            clock.t += wait
            assert _req(srv.port, "POST", path, auth)[0] == 200
            _, _, metrics = _req(srv.port, "GET", "/metrics")
            assert (
                "tpu_node_checker_api_server_rate_limited_total 1.0"
                in metrics.decode()
            )
        finally:
            srv.close()

    def test_auth_rejections_bypass_the_bucket(self):
        # 401s must not burn tokens: a scanner cannot starve the
        # legitimate token holder by being refused fast.
        clock = FakeClock()
        srv, calls = self._server(
            TokenBucket(rate=1.0, burst=1.0, monotonic=clock)
        )
        try:
            for _ in range(3):
                assert _req(
                    srv.port, "POST", "/api/v1/nodes/x/cordon",
                    {"Authorization": "Bearer wrong"},
                )[0] == 401
            node = json.loads(
                _req(srv.port, "GET", "/api/v1/nodes")[2]
            )["nodes"][0]["name"]
            assert _req(
                srv.port, "POST", f"/api/v1/nodes/{node}/cordon",
                {"Authorization": "Bearer s3cret"},
            )[0] == 200
        finally:
            srv.close()


# ---------------------------------------------------------------------------
# Stale-while-revalidate trend serving
# ---------------------------------------------------------------------------


class TestTrendSWR:
    def _cache(self, tmp_path, monkeypatch):
        log = tmp_path / "trend.jsonl"
        log.write_text(
            json.dumps({"ts": 1_700_000_000.0, "exit_code": 0}) + "\n"
        )
        release = threading.Event()
        builds = []

        real = checker.compute_trend_summary

        def gated(path):
            builds.append(path)
            if len(builds) > 1:  # rebuilds block until the test releases
                assert release.wait(timeout=10)
            return real(path)

        monkeypatch.setattr(checker, "compute_trend_summary", gated)
        return TrendCache(str(log)), log, release, builds

    def test_stale_served_during_rebuild_exactly_one_rebuild(
        self, tmp_path, monkeypatch
    ):
        cache, log, release, builds = self._cache(tmp_path, monkeypatch)
        first = cache.entity()  # first build: synchronous
        assert cache.rebuilds == 1 and len(builds) == 1
        assert cache.entity() is first  # steady state: cache hit
        with open(log, "a") as f:
            f.write(json.dumps({"ts": 1_700_000_060.0, "exit_code": 3}) + "\n")
        # Signature moved: readers get the STALE entity immediately while
        # the one rebuild blocks on the gate.
        for _ in range(3):
            assert cache.entity() is first
        assert cache.stale_served == 3
        assert len(builds) == 2  # exactly one background rebuild spawned
        release.set()
        deadline = time.monotonic() + 10
        while cache.rebuilds < 2 and time.monotonic() < deadline:
            time.sleep(0.005)  # tnc: allow-test-wall-clock(bounded 10s poll for the REAL tnc-trend-swr thread to commit its entity)
        assert cache.rebuilds == 2
        fresh = cache.entity()
        assert fresh is not first
        assert json.loads(fresh.raw)["rounds"] == 2
        assert len(builds) == 2  # the fresh entity is a cache hit, no rebuild

    def test_seq_move_with_unchanged_log_never_rebuilds(
        self, tmp_path, monkeypatch
    ):
        # The ISSUE 15 satellite pin, from the SWR side: the cache keys on
        # the trend-relevant content digest now, so a publication seq
        # advancing over an unmoved log is a pure cache hit — the old
        # (seq, signature) key spawned a full rebuild here every round.
        cache, _log, release, builds = self._cache(tmp_path, monkeypatch)
        release.set()
        first = cache.entity()
        for _ in range(6):  # one request per would-be publish
            assert cache.entity() is first
        assert cache.rebuilds == 1 and len(builds) == 1
        assert cache.stale_served == 0


# ---------------------------------------------------------------------------
# Publish-time compression: delta gz members, /metrics split
# ---------------------------------------------------------------------------


class TestPublishTimeCompression:
    def _rounds(self):
        nodes = fx.tpu_v5p_64_slice()[:8]
        r1 = _result(nodes)
        sick = [json.loads(json.dumps(n)) for n in nodes]
        sick[3]["status"]["conditions"][1]["status"] = "False"
        r2 = _result(sick)
        return nodes, r1, r2

    def test_delta_gz_members_decompress_to_the_full_body(self):
        nodes, r1, r2 = self._rounds()
        changed = {nodes[3]["metadata"]["name"]}
        prev = build_snapshot(r1.payload, r1.exit_code, 1, 100.0)
        delta = build_snapshot_delta(
            prev, r2.payload, r2.exit_code, 2, 200.0, changed
        )
        entity = delta.entities["nodes"]
        assert entity.gz is not None
        assert gzip.decompress(entity.gz) == entity.raw

    def test_unchanged_gz_fragments_reuse_by_reference(self):
        nodes, r1, r2 = self._rounds()
        changed = {nodes[3]["metadata"]["name"]}
        prev = build_snapshot(r1.payload, r1.exit_code, 1, 100.0)
        d1 = build_snapshot_delta(
            prev, r2.payload, r2.exit_code, 2, 200.0, changed
        )
        d2 = build_snapshot_delta(
            d1, r1.payload, r1.exit_code, 3, 300.0, changed
        )
        for n in nodes:
            name = n["metadata"]["name"]
            if name in changed:
                assert d2.node_gz_fragments[name] is not d1.node_gz_fragments[name]
            else:
                # Deflated once (the migration delta), reused forever after.
                assert d2.node_gz_fragments[name] is d1.node_gz_fragments[name]
        assert gzip.decompress(d2.entities["nodes"].gz) == d2.entities["nodes"].raw

    def test_metrics_gzip_is_member_concatenation_of_the_plain_body(self):
        srv = FleetStateServer(0, host="127.0.0.1")
        srv.publish(_result())
        try:
            status, headers, gz_body = _req(
                srv.port, "GET", "/metrics", {"Accept-Encoding": "gzip"}
            )
            assert status == 200 and headers["Content-Encoding"] == "gzip"
            text = gzip.decompress(gz_body).decode()
            # Round families from the cached prefix member + live stats
            # families from the per-scrape member, one coherent exposition.
            assert 'tpu_node_checker_chips{state="ready"} 256' in text
            assert "tpu_node_checker_api_server_requests_total" in text
            assert "tpu_node_checker_api_server_workers 1.0" in text
            assert "tpu_node_checker_api_server_swr_stale_served_total 0" in text
            plain = _req(srv.port, "GET", "/metrics")[2].decode()
            assert 'tpu_node_checker_chips{state="ready"} 256' in plain
        finally:
            srv.close()
