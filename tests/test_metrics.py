"""Metrics endpoint, JSONL state log, and instance-type inference tests."""

import json
import urllib.request

import pytest

from tests import fixtures as fx
from tpu_node_checker import checker, cli
from tpu_node_checker.detect import chips_per_host_from_instance_type, extract_node_info, group_slices
from tpu_node_checker.metrics import MetricsServer, render_metrics


def args_for(*argv):
    return cli.parse_args(list(argv))


class TestRenderMetrics:
    def _result(self, nodes, *extra):
        return checker.run_check(args_for(*extra), nodes=nodes)

    def test_families_present(self):
        text = render_metrics(self._result(fx.tpu_v5e_256_slice()))
        assert 'tpu_node_checker_nodes{state="ready"} 64' in text
        assert 'tpu_node_checker_chips{state="total"} 256' in text
        assert ('tpu_node_checker_slice_complete{nodepool="v5e-256-pool",'
                'slice="v5e-256-pool",topology="16x16"} 1.0') in text
        assert "tpu_node_checker_exit_code 0" in text
        assert "# TYPE tpu_node_checker_nodes gauge" in text

    def test_degraded_slice_zero(self):
        text = render_metrics(self._result(fx.tpu_v5p_64_slice(not_ready=2)))
        assert ('tpu_node_checker_slice_complete{nodepool="v5p-pool",'
                'slice="v5p-pool",topology="4x4x4"} 0.0') in text
        assert ('tpu_node_checker_slice_ready_chips{nodepool="v5p-pool",'
                'slice="v5p-pool",topology="4x4x4"} 56') in text

    def test_retry_and_degraded_families(self):
        result = self._result(fx.tpu_v5e_256_slice())
        result.payload["api_transport"] = {
            "connections_opened": 1,
            "requests_sent": 5,
            "requests_reused": 4,
            "retries": 3,
            "retries_by_reason": {"http_500": 2, "connection_reset": 1},
        }
        result.payload["degraded"] = True
        text = render_metrics(result)
        assert 'tpu_node_checker_api_retries_total{reason="http_500"} 2' in text
        assert 'tpu_node_checker_api_retries_total{reason="connection_reset"} 1' in text
        assert "# TYPE tpu_node_checker_api_retries_total counter" in text
        assert "tpu_node_checker_round_degraded 1.0" in text

    def test_zero_retries_render_as_zero_not_vanished(self):
        result = self._result(fx.tpu_v5e_256_slice())
        result.payload["api_transport"] = {
            "connections_opened": 1,
            "requests_sent": 1,
            "requests_reused": 0,
            "retries": 0,
        }
        text = render_metrics(result)
        # A healthy round must still emit the family (return-to-zero reads
        # as recovery, a vanished series reads as nothing).
        assert 'tpu_node_checker_api_retries_total{reason="none"} 0' in text
        assert "tpu_node_checker_round_degraded 0.0" in text

    def test_list_truncation_counter_rendered_by_resource(self):
        result = self._result(fx.tpu_v5e_256_slice())
        result.payload["api_transport"] = {
            "connections_opened": 1,
            "requests_sent": 25,
            "requests_reused": 24,
            "list_truncated": {"events": 3, "nodes": 1},
        }
        text = render_metrics(result)
        assert "# TYPE tpu_node_checker_api_list_truncated_total counter" in text
        assert 'tpu_node_checker_api_list_truncated_total{resource="events"} 3' in text
        assert 'tpu_node_checker_api_list_truncated_total{resource="nodes"} 1' in text
        # Healthy sessions omit the key and the family: absence IS the
        # pre-truncation-stat payload surface, byte for byte.
        del result.payload["api_transport"]["list_truncated"]
        assert "list_truncated" not in render_metrics(result)

    def test_breaker_gauges_rendered_when_state_supplied(self):
        result = self._result(fx.tpu_v5e_256_slice())
        text = render_metrics(
            result, breaker={"open": True, "consecutive_failures": 4}
        )
        assert "tpu_node_checker_watch_breaker_open 1.0" in text
        assert "tpu_node_checker_watch_breaker_consecutive_failures 4.0" in text
        # No breaker state (one-shot renders): no breaker families.
        assert "watch_breaker" not in render_metrics(result)

    def test_probe_telemetry_exported(self):
        result = self._result(fx.tpu_v5e_256_slice())
        result.payload["local_probe"] = {
            "ok": True,
            "level": "collective",
            "device_count": 4,
            "matmul_tflops": 3.9,
            "hbm_gbps": 2.2,
            "collective_busbw_gbps": 12.5,
            "ring_link_gbps": 40.0,
            "ici_axis_ok": {"t0": True},  # dict: exported as a labeled family
        }
        text = render_metrics(result)
        assert 'tpu_node_checker_probe_ok{level="collective"} 1.0' in text
        assert "tpu_node_checker_probe_devices 4" in text
        assert "tpu_node_checker_probe_matmul_tflops 3.9" in text
        assert "tpu_node_checker_probe_collective_busbw_gbps 12.5" in text
        assert "tpu_node_checker_probe_ring_link_gbps 40.0" in text
        # The dict never leaks as a raw scalar sample; it becomes the
        # per-axis family (test_fabric_fault_trending_families pins it).
        assert "tpu_node_checker_probe_ici_axis_ok {" not in text
        assert 'tpu_node_checker_probe_ici_axis_ok{axis="t0"} 1.0' in text

    def test_no_probe_no_probe_families(self):
        text = render_metrics(self._result(fx.tpu_v5e_256_slice()))
        assert "tpu_node_checker_probe_ok" not in text

    def test_fabric_fault_trending_families(self):
        # VERDICT r02 #9: per-axis verdicts and named bad links as series,
        # so fabric faults trend instead of living in one round's JSON.
        result = self._result(fx.tpu_v5e_256_slice())
        result.payload["local_probe"] = {
            "ok": False,
            "level": "collective",
            "collective_ok": True,
            "ring_ok": False,
            "ring_bad_links": ["3->4", "7->0"],
            "ici_axis_ok": {"t0": True, "t1": False},
        }
        text = render_metrics(result)
        assert "tpu_node_checker_probe_collective_ok 1.0" in text
        assert "tpu_node_checker_probe_ring_ok 0.0" in text
        assert 'tpu_node_checker_probe_ici_axis_ok{axis="t0"} 1.0' in text
        assert 'tpu_node_checker_probe_ici_axis_ok{axis="t1"} 0.0' in text
        assert "tpu_node_checker_probe_ring_bad_links 2" in text
        assert 'tpu_node_checker_probe_ring_bad_link{link="3->4"} 1.0' in text
        assert 'tpu_node_checker_probe_ring_bad_link{link="7->0"} 1.0' in text

    def test_healthy_ring_no_bad_link_series(self):
        result = self._result(fx.tpu_v5e_256_slice())
        result.payload["local_probe"] = {
            "ok": True,
            "level": "collective",
            "collective_ok": True,
            "ring_ok": True,
        }
        text = render_metrics(result)
        assert "tpu_node_checker_probe_ring_ok 1.0" in text
        assert "tpu_node_checker_probe_ring_bad_link" not in text
        assert "tpu_node_checker_probe_ici_axis_ok" not in text

    def test_probe_summary_families(self):
        # VERDICT r02 #5: the aggregator Deployment must be able to alert on
        # "N hosts probe-failed" from the scrape alone.
        result = self._result(fx.tpu_v5e_256_slice())
        result.payload["probe_summary"] = {
            "hosts_reported": 62,
            "hosts_ok": 60,
            "hosts_failed": ["gke-a", "gke-b"],
            "hosts_missing": ["gke-z"],
        }
        text = render_metrics(result)
        assert 'tpu_node_checker_probe_hosts{state="reported"} 62' in text
        assert 'tpu_node_checker_probe_hosts{state="ok"} 60' in text
        assert 'tpu_node_checker_probe_hosts{state="failed"} 2' in text
        assert 'tpu_node_checker_probe_hosts{state="missing"} 1' in text
        assert ('tpu_node_checker_probe_host_unhealthy'
                '{host="gke-a",state="failed"} 1.0') in text
        assert ('tpu_node_checker_probe_host_unhealthy'
                '{host="gke-z",state="missing"} 1.0') in text

    def test_probe_summary_all_healthy_no_per_host_series(self):
        result = self._result(fx.tpu_v5e_256_slice())
        result.payload["probe_summary"] = {
            "hosts_reported": 64,
            "hosts_ok": 64,
            "hosts_failed": [],
            "hosts_missing": [],
        }
        text = render_metrics(result)
        assert 'tpu_node_checker_probe_hosts{state="ok"} 64' in text
        assert "tpu_node_checker_probe_host_unhealthy" not in text

    def test_no_probe_summary_no_fleet_families(self):
        text = render_metrics(self._result(fx.tpu_v5e_256_slice()))
        assert "tpu_node_checker_probe_hosts" not in text

    def test_kind_mismatch_nodes_family(self):
        result = self._result(fx.tpu_v5e_256_slice())
        result.payload["nodes"][3]["probe"] = {
            "ok": True,
            "kind_mismatch": {"expected_generation": "v5e"},
        }
        text = render_metrics(result)
        assert "tpu_node_checker_kind_mismatch_nodes 1" in text

    def test_no_mismatch_no_kind_family(self):
        text = render_metrics(self._result(fx.tpu_v5e_256_slice()))
        assert "tpu_node_checker_kind_mismatch_nodes" not in text

    def test_probe_summary_per_host_series_capped(self):
        # A fleet-wide emitter outage must not mint one series per node.
        result = self._result(fx.tpu_v5e_256_slice())
        missing = [f"gke-{i:04d}" for i in range(150)]
        result.payload["probe_summary"] = {
            "hosts_reported": 0,
            "hosts_ok": 0,
            "hosts_failed": [],
            "hosts_missing": missing,
        }
        text = render_metrics(result)
        assert text.count("tpu_node_checker_probe_host_unhealthy{") == 100
        assert "tpu_node_checker_probe_host_unhealthy_omitted 50" in text
        assert 'tpu_node_checker_probe_hosts{state="missing"} 150' in text

    def test_multislice_families(self):
        text = render_metrics(
            self._result(fx.tpu_multislice(n_slices=2, not_ready=1))
        )
        assert 'tpu_node_checker_multislice_complete{group="ms-train-1"} 0.0' in text
        assert 'tpu_node_checker_multislice_ready_chips{group="ms-train-1"} 28' in text
        assert 'tpu_node_checker_multislice_slices{group="ms-train-1"} 2' in text

    def test_no_multislice_no_families(self):
        text = render_metrics(self._result(fx.tpu_v5e_256_slice()))
        assert "tpu_node_checker_multislice" not in text

    def test_cordon_families(self):
        result = self._result(fx.tpu_v5e_256_slice())
        result.payload["cordon"] = {
            "dry_run": False,
            "cordoned": ["a"],
            "failed": [],
            "already_cordoned": 0,
            "skipped_over_cap": ["b", "c"],
        }
        text = render_metrics(result)
        assert "tpu_node_checker_cordoned_nodes 1" in text
        assert "tpu_node_checker_cordon_skipped_over_cap 2" in text

    def test_single_host_slice_pool_unique_series(self):
        # N single-host slices in one pool share nodepool+topology; the
        # "slice" label must keep every series unique or Prometheus drops
        # the whole scrape as duplicate samples.
        nodes = [
            fx.make_node(
                f"oneh-{i}",
                allocatable={"google.com/tpu": "4"},
                labels={
                    "cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-device",
                    "cloud.google.com/gke-tpu-topology": "2x2",
                    "cloud.google.com/gke-nodepool": "onehost",
                },
            )
            for i in range(3)
        ]
        text = render_metrics(self._result(nodes))
        complete_lines = [
            l for l in text.splitlines()
            if l.startswith("tpu_node_checker_slice_complete{")
        ]
        assert len(complete_lines) == 3
        assert len(set(complete_lines)) == 3  # all series distinct
        assert 'slice="oneh-0"' in text

    def test_mark_error_flips_exit_code_keeps_gauges(self):
        from tpu_node_checker.metrics import MetricsServer

        server = MetricsServer(0, host="127.0.0.1")
        try:
            server.update(self._result(fx.tpu_v5e_256_slice()))
            server.mark_error(1)
            body = server._body.decode()
            assert "tpu_node_checker_exit_code 1" in body
            assert 'tpu_node_checker_chips{state="ready"} 256' in body  # last known
            assert "\ntpu_node_checker_last_run_timestamp_seconds " not in body
        finally:
            server.close()

    def test_label_escaping(self):
        nodes = fx.tpu_v5e_single_host()
        nodes[0]["metadata"]["labels"]["cloud.google.com/gke-nodepool"] = 'we"ird\npool'
        text = render_metrics(self._result(nodes))
        assert r'nodepool="we\"ird\npool"' in text


class TestMetricsServer:
    def test_serves_latest_result(self):
        server = MetricsServer(0, host="127.0.0.1")
        try:
            url = f"http://127.0.0.1:{server.port}/metrics"
            body = urllib.request.urlopen(url, timeout=5).read().decode()
            assert "no check completed yet" in body
            result = checker.run_check(args_for(), nodes=fx.tpu_v5e_256_slice())
            server.update(result)
            body = urllib.request.urlopen(url, timeout=5).read().decode()
            assert 'tpu_node_checker_chips{state="ready"} 256' in body
            assert (
                urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}/metrics", timeout=5
                ).status
                == 200
            )
        finally:
            server.close()

    def test_unknown_path_404(self):
        import urllib.error

        server = MetricsServer(0, host="127.0.0.1")
        try:
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}/nope", timeout=5
                )
                raised = False
            except urllib.error.HTTPError as e:
                raised = e.code == 404
            assert raised
        finally:
            server.close()


class TestStateLog:
    def test_one_shot_appends(self, tmp_path, capsys):
        log = tmp_path / "state.jsonl"
        code = checker.one_shot(
            args_for("--log-jsonl", str(log)), nodes=fx.tpu_v5p_64_slice(not_ready=1)
        )
        assert code == 0
        entry = json.loads(log.read_text().strip())
        assert entry["ready_chips"] == 60
        assert entry["slices_complete"] == 0
        assert entry["exit_code"] == 0
        assert "ts" in entry and "duration_ms" in entry

    def test_appends_accumulate(self, tmp_path, capsys):
        log = tmp_path / "state.jsonl"
        for _ in range(3):
            checker.one_shot(args_for("--log-jsonl", str(log)), nodes=fx.gpu_pool(1))
        assert len(log.read_text().splitlines()) == 3

    def test_unwritable_log_not_fatal(self, capsys):
        code = checker.one_shot(
            args_for("--log-jsonl", "/nonexistent-dir/state.jsonl"),
            nodes=fx.gpu_pool(1),
        )
        assert code == 0
        assert "Cannot append state log" in capsys.readouterr().err


class TestInstanceTypeInference:
    def test_parse(self):
        assert chips_per_host_from_instance_type("ct5lp-hightpu-4t") == 4
        assert chips_per_host_from_instance_type("ct5lp-hightpu-8t") == 8
        assert chips_per_host_from_instance_type("ct6e-standard-4t") == 4
        assert chips_per_host_from_instance_type("n1-standard-8") is None
        assert chips_per_host_from_instance_type(None) is None

    def test_fully_dead_device_plugins_still_exit_3_with_expectations(self, capsys):
        # Every host of the slice has a completely dead device plugin: no
        # allocatable, no capacity, only the GKE TPU labels. The cluster must
        # grade exit 3 (nodes exist, unusable) — not exit 2 — and the slice
        # expectation must come from the machine type (ct5p-hightpu-4t → 4
        # chips/host → 16 hosts for 4x4x4).
        nodes = [
            fx.make_node(
                f"dead-{i}",
                ready=True,  # kubelet happy, device plugin dead
                allocatable={},
                capacity={},
                labels={
                    "cloud.google.com/gke-tpu-accelerator": "tpu-v5p-slice",
                    "cloud.google.com/gke-tpu-topology": "4x4x4",
                    "cloud.google.com/gke-nodepool": "p",
                    "node.kubernetes.io/instance-type": "ct5p-hightpu-4t",
                },
            )
            for i in range(16)
        ]
        code = checker.one_shot(args_for("--json"), nodes=nodes)
        assert code == 3
        payload = json.loads(capsys.readouterr().out)
        assert payload["total_nodes"] == 16
        assert payload["ready_nodes"] == 0
        s = payload["slices"][0]
        assert s["expected_hosts"] == 16
        assert s["expected_chips"] == 64
        assert s["complete"] is False


class TestTrendSummary:
    """--trend FILE: post-incident analysis of the --log-jsonl record."""

    def _log(self, tmp_path, entries):
        p = tmp_path / "trend.jsonl"
        p.write_text("\n".join(json.dumps(e) for e in entries) + "\n")
        return str(p)

    def _entries(self):
        # 10:00 ok, 10:01 ok, 10:02 degraded, 10:03 degraded, 10:04 ok,
        # 10:05 monitor error — availability 3/6, two outages (120s, open 0s).
        t0 = 1_700_000_000
        codes = [0, 0, 3, 3, 0, 1]
        return [
            {
                "ts": t0 + i * 60,
                "exit_code": c,
                "total_chips": 256,
                "ready_chips": 256 if c == 0 else 192,
            }
            for i, c in enumerate(codes)
        ]

    def test_json_summary(self, tmp_path, capsys):
        path = self._log(tmp_path, self._entries())
        assert cli.main(["--trend", path, "--json"]) == 0
        s = json.loads(capsys.readouterr().out)
        assert s["rounds"] == 6
        assert s["availability_pct"] == 50.0
        assert s["window_s"] == 300.0
        assert s["transitions_total"] == 3  # 0→3, 3→0, 0→1
        assert [t["to"] for t in s["transitions"]] == [3, 0, 1]
        assert s["longest_outage_s"] == 120.0  # 10:02 → 10:04
        assert s["last_exit_code"] == 1
        # Chip availability: 3 rounds at 100%, 3 at 75% → 87.5%.
        assert s["chip_availability_pct"] == 87.5
        # Occupancy: 5 intervals of 60s charged to the EARLIER state, plus
        # one median interval (60s) for the final round (exit 1) — an outage
        # still in progress at the end of the log must carry weight.
        assert s["state_seconds"] == {"0": 180.0, "1": 60.0, "3": 120.0}
        assert s["time_weighted_availability_pct"] == 50.0

    def test_slice_availability(self, tmp_path, capsys):
        t0 = 1_700_000_000
        entries = [
            {"ts": t0, "exit_code": 0, "slices": 4, "slices_complete": 4},
            {"ts": t0 + 60, "exit_code": 3, "slices": 4, "slices_complete": 2},
        ]
        path = self._log(tmp_path, entries)
        assert cli.main(["--trend", path, "--json"]) == 0
        s = json.loads(capsys.readouterr().out)
        assert s["slice_availability_pct"] == 75.0  # mean of 100% and 50%
        assert s["chip_availability_pct"] is None  # no chip fields logged
        # A log ENDING degraded must not report inflated time-weighted
        # availability: the trailing exit-3 round carries a median interval.
        assert s["state_seconds"] == {"0": 60.0, "3": 60.0}
        assert s["time_weighted_availability_pct"] == 50.0

    def test_human_summary(self, tmp_path, capsys):
        path = self._log(tmp_path, self._entries())
        assert cli.main(["--trend", path]) == 0
        out = capsys.readouterr().out
        assert "6 rounds over 300.0s" in out
        assert "availability: 50.0% of rounds at exit 0" in out
        assert "exit 0 → 3" in out
        assert "longest outage 120.0s" in out

    def test_timestamps_render_in_utc(self, tmp_path, capsys):
        # 1_700_000_000 = 2023-11-14 22:13:20 UTC.  Local-time rendering
        # would shift this by the host TZ and misalign incident timelines.
        path = self._log(tmp_path, self._entries())
        assert cli.main(["--trend", path]) == 0
        out = capsys.readouterr().out
        assert "2023-11-14 22:13:20Z" in out

    def test_transition_names_causes(self, tmp_path, capsys):
        # A degraded round's logged causes ride on the transition line, so
        # --trend answers WHICH slice caused the outage, not only when.
        t0 = 1_700_000_000
        entries = [
            {"ts": t0, "exit_code": 0},
            {"ts": t0 + 60, "exit_code": 3,
             "causes": ["slice pool-a: 14/16 hosts ready", "probe-failed: h3"]},
            {"ts": t0 + 120, "exit_code": 0},
        ]
        path = self._log(tmp_path, entries)
        assert cli.main(["--trend", path, "--json"]) == 0
        s = json.loads(capsys.readouterr().out)
        assert s["transitions"][0]["causes"] == [
            "slice pool-a: 14/16 hosts ready", "probe-failed: h3"
        ]
        assert "causes" not in s["transitions"][1]  # recovery needs none
        assert cli.main(["--trend", path]) == 0
        out = capsys.readouterr().out
        assert "(slice pool-a: 14/16 hosts ready; probe-failed: h3)" in out

    def test_top_causes_roll_up_by_class(self, tmp_path, capsys):
        # Across ALL degraded rounds (not only transitions): names fold
        # into classes — a 64-host outage is one cause — and the NotReady
        # kubelet reason survives the fold.
        t0 = 1_700_000_000
        entries = [
            {"ts": t0, "exit_code": 0},
            {"ts": t0 + 60, "exit_code": 3, "causes": [
                "slice pool-a: 14/16 hosts ready",
                "slice pool-b: 15/16 hosts ready",
                "not-ready: h1 (KubeletNotReady: runtime down)",
                "not-ready: h2 (KubeletNotReady: runtime down)",
                "not-ready: h3 (NodeStatusUnknown: kubelet stopped)",
                "+9 more",
            ]},
            {"ts": t0 + 120, "exit_code": 3, "causes": [
                "slice pool-a: 14/16 hosts ready",
                "probe-failed: h4",
            ]},
            {"ts": t0 + 180, "exit_code": 1, "error": "API unreachable"},
            {"ts": t0 + 240, "exit_code": 0},
        ]
        path = self._log(tmp_path, entries)
        assert cli.main(["--trend", path, "--json"]) == 0
        s = json.loads(capsys.readouterr().out)
        # Per-round dedup: two slices in round 1 count that ROUND once per
        # class; the cap line vanishes.
        assert s["top_causes"][0] == {"cause": "slice incomplete", "rounds": 2}
        by_cause = {c["cause"]: c["rounds"] for c in s["top_causes"]}
        assert by_cause["not-ready (KubeletNotReady)"] == 1
        assert by_cause["monitor error"] == 1
        assert "+9 more" not in by_cause
        assert s["cause_classes_total"] == 5
        # A message-only condition ("not-ready: h (container runtime is
        # down)") must not promote its first word to a reason class.
        from tpu_node_checker.checker import _cause_class

        assert (
            _cause_class("not-ready: h1 (container runtime is down)")
            == "not-ready"
        )
        assert (
            _cause_class("not-ready: h1 (KubeletNotReady)")
            == "not-ready (KubeletNotReady)"
        )
        assert (
            _cause_class("not-ready: h1 (KubeletNotReady, NetworkUnavailable: x)")
            == "not-ready (KubeletNotReady)"
        )
        # '+'-joined adverse lists class consistently whether one or many.
        assert (
            _cause_class("not-ready: h1 (DiskPressure+PIDPressure)")
            == "not-ready (DiskPressure+PIDPressure)"
        )
        # A lowercase single-word message is never promoted to a reason.
        assert _cause_class("not-ready: h1 (unreachable)") == "not-ready"
        # Human mode prints the same roll-up.
        assert cli.main(["--trend", path]) == 0
        out = capsys.readouterr().out
        assert "top causes: slice incomplete ×2" in out

    def test_empty_log_machine_readable_no_traceback(self, tmp_path, capsys):
        # An empty (or never-written-to) log is a normal first-day state for
        # automation polling --trend --json: stdout must still parse, exit 1
        # is the signal, and no traceback leaks.
        p = tmp_path / "empty.jsonl"
        p.write_text("")
        assert cli.main(["--trend", str(p), "--json"]) == 1
        captured = capsys.readouterr()
        s = json.loads(captured.out)
        assert s["rounds"] == 0
        assert "no usable rounds" in s["error"]
        assert "Traceback" not in captured.err
        # Human mode: the stderr note, still no traceback, nothing on stdout.
        assert cli.main(["--trend", str(p)]) == 1
        captured = capsys.readouterr()
        assert captured.out == ""
        assert "Traceback" not in captured.err

    def test_whitespace_only_log_machine_readable(self, tmp_path, capsys):
        p = tmp_path / "ws.jsonl"
        p.write_text("\n   \n\t\n")
        assert cli.main(["--trend", str(p), "--json"]) == 1
        s = json.loads(capsys.readouterr().out)
        assert s == {"rounds": 0, "skipped_lines": 0, "error": "has no usable rounds"}

    def test_torn_final_line_counted_not_fatal(self, tmp_path, capsys):
        # A crash mid-append tears the last line; the analysis must proceed
        # over the intact rounds and count the torn one — the exact same
        # loader the history store uses (history/store.read_jsonl_tolerant).
        p = tmp_path / "torn.jsonl"
        p.write_text(
            json.dumps({"ts": 1_700_000_000, "exit_code": 0}) + "\n"
            + json.dumps({"ts": 1_700_000_060, "exit_code": 3}) + "\n"
            + '{"ts": 1700000120, "exit_co'
        )
        assert cli.main(["--trend", str(p), "--json"]) == 0
        s = json.loads(capsys.readouterr().out)
        assert s["rounds"] == 2
        assert s["skipped_lines"] == 1

    def test_unreadable_log_machine_readable_in_json_mode(self, tmp_path, capsys):
        assert cli.main(["--trend", str(tmp_path / "absent.jsonl"), "--json"]) == 1
        captured = capsys.readouterr()
        s = json.loads(captured.out)
        assert s["rounds"] == 0 and "unreadable" in s["error"]
        assert "Traceback" not in captured.err

    def test_trend_over_emitter_round_log(self, tmp_path, capsys):
        # The emitter loop's --log-jsonl shape is --trend-compatible: a
        # DaemonSet pod's own probe history trends like an aggregator's.
        t0 = 1_700_000_000
        entries = [
            {"ts": t0, "exit_code": 0, "probe_ok": True,
             "probe_level": "compute", "duration_ms": 900.0},
            {"ts": t0 + 300, "exit_code": 3, "probe_ok": False,
             "probe_level": "compute", "duration_ms": 950.0,
             "causes": ["probe-failed: h1 (matmul mismatch)"]},
            {"ts": t0 + 600, "exit_code": 0, "probe_ok": True,
             "probe_level": "compute", "duration_ms": 910.0},
        ]
        path = self._log(tmp_path, entries)
        assert cli.main(["--trend", path, "--json"]) == 0
        s = json.loads(capsys.readouterr().out)
        assert s["availability_pct"] == pytest.approx(66.67, abs=0.01)
        assert s["top_causes"] == [{"cause": "probe-failed", "rounds": 1}]
        assert s["transitions"][0]["causes"] == [
            "probe-failed: h1 (matmul mismatch)"
        ]

    def test_fuzz_trend_reader_is_total(self, tmp_path, capsys):
        # The trend log is operator-writable (and crash-appendable): ANY
        # file content must yield exit 0 (usable rounds exist) or exit 1 —
        # never a traceback that sinks post-incident analysis.
        from hypothesis import given, settings
        from hypothesis import strategies as st

        # NaN/inf stay ON: json round-trips them (NaN/Infinity) and the
        # reader must skip such lines, not crash the UTC formatter.
        json_vals = fx.json_value_strategy(text_size=8, max_leaves=8)
        entry_ish = st.one_of(
            json_vals,
            st.fixed_dictionaries(
                {},
                optional={
                    "ts": json_vals, "exit_code": json_vals,
                    "causes": json_vals, "planned": json_vals,
                    "total_chips": json_vals, "ready_chips": json_vals,
                    "slices": json_vals, "slices_complete": json_vals,
                    "error": json_vals,
                },
            ),
        )

        @settings(max_examples=60, deadline=None)
        @given(st.lists(entry_ish, max_size=6), st.booleans())
        def run(entries, json_mode):
            path = tmp_path / "fuzz.jsonl"
            path.write_text(
                "".join(json.dumps(e) + "\n" for e in entries) + "{not json\n"
            )
            rc = checker.trend_summary(str(path), json_mode=json_mode)
            assert rc in (0, 1)
            capsys.readouterr()

        run()

    def test_monitor_error_transition_carries_error(self, tmp_path, capsys):
        t0 = 1_700_000_000
        entries = [
            {"ts": t0, "exit_code": 0},
            {"ts": t0 + 60, "exit_code": 1, "error": "API unreachable"},
        ]
        path = self._log(tmp_path, entries)
        assert cli.main(["--trend", path, "--json"]) == 0
        s = json.loads(capsys.readouterr().out)
        assert s["transitions"][0]["causes"] == ["monitor error: API unreachable"]

    def test_degraded_round_logs_causes_end_to_end(self, tmp_path, capsys):
        # one_shot on a degraded fixture must write a causes list that names
        # the incomplete slice — the log's payload had the names all along.
        log = tmp_path / "log.jsonl"
        code = checker.one_shot(
            args_for("--strict-slices", "--log-jsonl", str(log)),
            nodes=fx.tpu_v5p_64_slice(not_ready=2),
        )
        assert code == 3
        entry = json.loads(log.read_text().splitlines()[-1])
        assert any("slice" in c and "hosts ready" in c for c in entry["causes"])
        capsys.readouterr()

    def test_capacity_shortfall_logs_cause(self, tmp_path, capsys):
        # --expected-chips outage: every PRESENT node is Ready and every
        # present slice complete (the missing nodepool is invisible), so the
        # capacity assertion itself must supply the cause line.
        log = tmp_path / "log.jsonl"
        code = checker.one_shot(
            args_for(
                "--expected-chips", "google.com/tpu=256",
                "--log-jsonl", str(log),
            ),
            nodes=fx.tpu_v5e_single_host(),
        )
        assert code == 3
        entry = json.loads(log.read_text().splitlines()[-1])
        assert any("expected ≥256 google.com/tpu chips" in c for c in entry["causes"])
        capsys.readouterr()

    def test_no_accel_nodes_logs_cause(self, tmp_path, capsys):
        log = tmp_path / "log.jsonl"
        code = checker.one_shot(
            args_for("--log-jsonl", str(log)), nodes=fx.cpu_only_cluster()
        )
        assert code == 2
        entry = json.loads(log.read_text().splitlines()[-1])
        assert entry["causes"] == ["no accelerator nodes"]
        capsys.readouterr()

    def test_healthy_round_logs_no_causes(self, tmp_path, capsys):
        log = tmp_path / "log.jsonl"
        code = checker.one_shot(
            args_for("--log-jsonl", str(log)), nodes=fx.tpu_v5e_single_host()
        )
        assert code == 0
        entry = json.loads(log.read_text().splitlines()[-1])
        assert "causes" not in entry
        capsys.readouterr()

    def test_malformed_lines_skipped_and_counted(self, tmp_path, capsys):
        entries = self._entries()
        p = tmp_path / "trend.jsonl"
        lines = [json.dumps(e) for e in entries]
        lines.insert(2, "{torn write")
        p.write_text("\n".join(lines) + "\n")
        assert cli.main(["--trend", str(p), "--json"]) == 0
        s = json.loads(capsys.readouterr().out)
        assert s["rounds"] == 6
        assert s["skipped_lines"] == 1

    def test_missing_or_empty_log_exits_1(self, tmp_path, capsys):
        assert cli.main(["--trend", str(tmp_path / "nope.jsonl")]) == 1
        empty = tmp_path / "empty.jsonl"
        empty.write_text("")
        assert cli.main(["--trend", str(empty)]) == 1

    def test_runs_alone(self, capsys):
        import pytest as _pytest

        with _pytest.raises(SystemExit) as exc:
            cli.parse_args(["--trend", "f.jsonl", "--probe"])
        assert exc.value.code == 2
        assert "--trend runs alone" in capsys.readouterr().err
