"""DCN fault-domain localization (VERDICT r03 #2).

A multislice job joins several ICI tori over the data-center network; a
fault on the slice boundary and a fault inside a torus are different cables
and different repairs.  The probe builds a hybrid mesh — one leading ``dcn``
axis over slices × the per-slice ICI axes — and runs the same per-axis psum
legs over it, so the verdict names "dcn" vs "ici axis k", plus a psum pinned
to the dcn axis for a cross-slice bandwidth figure.

CPU devices carry no ``slice_index``, so the multislice shape is rehearsed
with ``TNC_CHAOS_SLICES=N`` (a contiguous partition, stamped via
``chaos_injected`` like every chaos hook) — exactly what an operator uses to
rehearse the DCN path on a single real slice.
"""

import numpy as np
import pytest

from tpu_node_checker.parallel import (
    axis_bandwidth_probe,
    hybrid_mesh,
    per_axis_probe,
)
from tpu_node_checker.probe.liveness import run_local_probe


class TestHybridMesh:
    def test_partition_with_matching_topology(self):
        m = hybrid_mesh(num_slices=2, topology="2x2")
        assert tuple(m.axis_names) == ("dcn", "t0", "t1")
        assert m.devices.shape == (2, 2, 2)

    def test_partition_without_topology_is_flat_per_slice(self):
        m = hybrid_mesh(num_slices=2, topology=None)
        assert tuple(m.axis_names) == ("dcn", "d")
        assert m.devices.shape == (2, 4)

    def test_mismatched_topology_falls_back_flat(self):
        # "4x4" promises 16 chips/slice; 4 present → flat intra-slice axis,
        # never a wrong-shaped torus.
        m = hybrid_mesh(num_slices=2, topology="4x4")
        assert tuple(m.axis_names) == ("dcn", "d")

    def test_rejects_non_multislice_device_sets(self):
        with pytest.raises(ValueError, match="not a multislice"):
            hybrid_mesh(num_slices=None)  # CPU devices have no slice_index
        with pytest.raises(ValueError, match=">= 2"):
            hybrid_mesh(num_slices=1)
        with pytest.raises(ValueError, match="partition"):
            hybrid_mesh(num_slices=3)  # 8 % 3 != 0

    def test_groups_by_real_slice_index_when_present(self):
        import jax

        class FakeDev:
            # Minimal device stand-in: hybrid_mesh only reads these two.
            def __init__(self, id, slice_index):
                self.id, self.slice_index = id, slice_index

        devs = [FakeDev(i, i // 2) for i in range(8)]  # 4 slices of 2
        m = hybrid_mesh(devices=devs)
        assert m.devices.shape == (4, 2)
        assert [d.slice_index for d in m.devices[:, 0].flat] == [0, 1, 2, 3]
        del jax

    def test_unequal_slices_rejected(self):
        class FakeDev:
            def __init__(self, id, slice_index):
                self.id, self.slice_index = id, slice_index

        devs = [FakeDev(i, 0 if i < 3 else 1) for i in range(8)]
        with pytest.raises(ValueError, match="unequal"):
            hybrid_mesh(devices=devs)


class TestDcnProbes:
    def test_per_axis_over_hybrid_localizes_dcn(self):
        m = hybrid_mesh(num_slices=2, topology="2x2")
        r = per_axis_probe(mesh=m, inject_fault_axis="dcn")
        assert not r.ok
        assert r.details["axis_ok"] == {"dcn": False, "t0": True, "t1": True}
        assert "DCN slice boundary" in r.error

    def test_ici_fault_does_not_blame_dcn(self):
        m = hybrid_mesh(num_slices=2, topology="2x2")
        r = per_axis_probe(mesh=m, inject_fault_axis="t1")
        assert not r.ok
        assert r.details["axis_ok"] == {"dcn": True, "t0": True, "t1": False}
        assert "t1" in r.error and "DCN" not in r.error

    def test_axis_bandwidth_probe_verifies_and_measures(self):
        m = hybrid_mesh(num_slices=2, topology="2x2")
        r = axis_bandwidth_probe(m, "dcn", payload=1 << 14)
        assert r.ok, r.error
        assert r.details["axis"] == "dcn"
        assert r.details["axis_size"] == 2
        assert r.details["busbw_gbps"] is not None and r.details["busbw_gbps"] > 0

    def test_axis_bandwidth_probe_unknown_axis(self):
        m = hybrid_mesh(num_slices=2)
        r = axis_bandwidth_probe(m, "nope")
        assert not r.ok and "nope" in r.error

    def test_exactness_at_large_payload(self):
        # The mod-256 payload keeps every reduction an exact f32 integer
        # even at multi-MiB payloads — a plain position index would round.
        m = hybrid_mesh(num_slices=2, topology="2x2")
        r = axis_bandwidth_probe(m, "dcn", payload=1 << 20)
        assert r.ok, r.error


@pytest.mark.slow  # six collective-level probe children (~110s); CI's slow step covers them
class TestDcnInProbeChild:
    """End-to-end through the subprocess child on the CPU mesh."""

    def test_chaos_slices_runs_dcn_legs_and_passes(self, monkeypatch):
        monkeypatch.setenv("TNC_CHAOS_SLICES", "2")
        r = run_local_probe(level="collective", timeout_s=300, topology="2x2")
        assert r.ok, r.error
        assert r.details["chaos_injected"] == {"slices": 2}
        assert r.details["fault_domain_ok"] == {
            "dcn": True, "t0": True, "t1": True,
        }
        assert r.details["fault_domain_topology"] == "2x2x2"
        assert r.details.get("dcn_busbw_gbps") is not None
        bw = r.details["fault_domain_busbw_gbps"]
        assert set(bw) == {"dcn", "t0", "t1"}
        assert all(isinstance(v, (int, float)) and v > 0 for v in bw.values())
        assert bw["dcn"] == r.details["dcn_busbw_gbps"]

    def test_chaos_dcn_fault_is_named(self, monkeypatch):
        # The VERDICT's done-criterion: fake two slices, inject
        # TNC_CHAOS_AXIS=dcn, and the report names the DCN axis.
        monkeypatch.setenv("TNC_CHAOS_SLICES", "2")
        monkeypatch.setenv("TNC_CHAOS_AXIS", "dcn")
        r = run_local_probe(level="collective", timeout_s=300, topology="2x2")
        assert not r.ok
        assert r.details["chaos_injected"] == {"slices": 2, "axis": "dcn"}
        assert r.details["fault_domain_ok"]["dcn"] is False
        assert r.details["fault_domain_ok"]["t0"] is True
        assert "DCN slice boundary" in (r.error or "")

    def test_chaos_ici_axis_fault_inside_multislice_names_the_axis(self, monkeypatch):
        monkeypatch.setenv("TNC_CHAOS_SLICES", "2")
        monkeypatch.setenv("TNC_CHAOS_AXIS", "t0")
        r = run_local_probe(level="collective", timeout_s=300, topology="2x2")
        assert not r.ok
        assert r.details["fault_domain_ok"] == {
            "dcn": True, "t0": False, "t1": True,
        }
        assert "t0" in (r.error or "")

    def test_chaos_dcn_axis_without_multislice_fails_loudly(self, monkeypatch):
        # Injecting a DCN fault with no second slice would test nothing.
        monkeypatch.setenv("TNC_CHAOS_AXIS", "dcn")
        r = run_local_probe(level="collective", timeout_s=300, topology="2x4")
        assert not r.ok
        assert "TNC_CHAOS_AXIS=dcn" in (r.error or "")
        assert "TNC_CHAOS_SLICES" in (r.error or "")

    def test_malformed_slice_count_fails_loudly(self, monkeypatch):
        monkeypatch.setenv("TNC_CHAOS_SLICES", "two")
        r = run_local_probe(level="collective", timeout_s=300)
        assert not r.ok
        assert "TNC_CHAOS_SLICES" in (r.error or "")
        assert r.details.get("chaos_injected") == {"slices": "two"}

    def test_single_slice_count_fails_loudly(self, monkeypatch):
        # TNC_CHAOS_SLICES=1 would skip the whole DCN block — the rehearsal
        # would pass while testing nothing.
        monkeypatch.setenv("TNC_CHAOS_SLICES", "1")
        r = run_local_probe(level="collective", timeout_s=300)
        assert not r.ok
        assert "at least 2" in (r.error or "")
        assert r.details.get("chaos_injected") == {"slices": 1}


class TestDcnMetrics:
    def test_fault_domain_and_dcn_bandwidth_families(self):
        from tpu_node_checker.checker import CheckResult
        from tpu_node_checker.metrics import render_metrics

        result = CheckResult(exit_code=0)
        result.payload = {
            "total_nodes": 1, "ready_nodes": 1, "slices": [],
            "local_probe": {
                "ok": False, "level": "collective",
                "fault_domain_ok": {"dcn": False, "t0": True},
                "dcn_busbw_gbps": 12.5,
            },
            "timings_ms": {"total": 1.0},
        }
        text = render_metrics(result)
        assert 'tpu_node_checker_probe_fault_domain_ok{axis="dcn"} 0.0' in text
        assert 'tpu_node_checker_probe_fault_domain_ok{axis="t0"} 1.0' in text
        assert "tpu_node_checker_probe_dcn_busbw_gbps 12.5" in text
