"""Property-based fuzzing of the detection core.

The reference's defensive null-handling (check-gpu-node.py:173,184,203-211)
is a behavior contract: *no* node object shape may crash the checker.  These
properties throw arbitrary JSON-ish structures at the pure core and assert
totality plus the invariants the exit-code contract rests on.
"""

import json

from hypothesis import given, settings, strategies as st

from tpu_node_checker.detect import (
    extract_node_info,
    group_slices,
    is_ready,
    select_accelerator_nodes,
)
from tests import fixtures as fx
from tpu_node_checker.utils.quantity import parse_quantity

# JSON-ish scalars that could appear anywhere in a node object.
scalars = st.one_of(
    st.none(),
    st.booleans(),
    st.integers(min_value=-(10**18), max_value=10**18),
    st.floats(allow_nan=True, allow_infinity=True),
    st.text(max_size=30),
)

# Bias the fuzzer onto the TPU-specific paths: purely random keys essentially
# never hit the GKE labels/resource keys, leaving slice grouping, topology
# parsing, and nodepool handling unexercised by the totality property.
_KNOWN_LABEL_KEYS = (
    "cloud.google.com/gke-tpu-accelerator",
    "cloud.google.com/gke-tpu-topology",
    "cloud.google.com/gke-nodepool",
    "node.kubernetes.io/instance-type",
)
_KNOWN_RESOURCE_KEYS = (
    "google.com/tpu",
    "cloud-tpus.google.com/v5e",
    "nvidia.com/gpu",
    "amd.com/gpu",
)
label_keys = st.one_of(st.sampled_from(_KNOWN_LABEL_KEYS), st.text(max_size=40))
resource_keys = st.one_of(st.sampled_from(_KNOWN_RESOURCE_KEYS), st.text(max_size=40))
# Values biased toward topology-shaped strings so parse_topology runs hot.
label_values = st.one_of(
    scalars, st.sampled_from(("2x2x1", "16x16", "8x", "x", "0x4", "tpu-v5e-pool"))
)

json_values = fx.json_value_strategy(text_size=20, max_leaves=20)

# Node-shaped but with garbage in every slot.
node_like = st.fixed_dictionaries(
    {},
    optional={
        "metadata": st.one_of(
            json_values,
            st.fixed_dictionaries(
                {},
                optional={
                    "name": scalars,
                    "labels": st.dictionaries(label_keys, label_values, max_size=5),
                },
            ),
        ),
        "spec": st.one_of(
            json_values,
            st.fixed_dictionaries({}, optional={"taints": st.lists(json_values, max_size=3)}),
        ),
        "status": st.one_of(
            json_values,
            st.fixed_dictionaries(
                {},
                optional={
                    "allocatable": st.dictionaries(resource_keys, scalars, max_size=6),
                    "capacity": st.dictionaries(resource_keys, scalars, max_size=6),
                    "conditions": st.lists(json_values, max_size=3),
                },
            ),
        ),
    },
)


def _normalize(node):
    """Keep inputs JSON-shaped: dict at top level, like a real API response."""
    return node if isinstance(node, dict) else {"metadata": node}


@settings(max_examples=300, deadline=None)
@given(st.lists(node_like, max_size=6))
def test_pipeline_is_total(nodes):
    """No input shape may raise; all invariants of the analyzed output hold."""
    nodes = [_normalize(n) for n in nodes]
    try:
        accel, ready = select_accelerator_nodes(nodes)
    except (TypeError, AttributeError) as exc:  # defensive contract violated
        raise AssertionError(f"detection crashed on {json.dumps(nodes, default=str)[:500]}: {exc}")
    assert set(map(id, ready)) <= set(map(id, accel))
    for info in accel:
        assert info.accelerators >= 0
        assert sum(info.breakdown.values()) == info.accelerators or info.accelerators == 0
        d = info.to_dict()
        json.dumps(d)  # payload must always be serializable
    slices = group_slices(accel)
    for s in slices:
        assert 0 <= len(s.ready_hosts) <= len(s.hosts)
        json.dumps(s.to_dict())


@settings(max_examples=300, deadline=None)
@given(node_like)
def test_is_ready_total(node):
    assert is_ready(_normalize(node)) in (True, False)


@settings(max_examples=500, deadline=None)
@given(scalars)
def test_parse_quantity_total(raw):
    out = parse_quantity(raw)
    assert out is None or isinstance(out, int)
