"""Keep-alive pool + bounded fan-out: the transport-layer perf contract.

BENCH_r05 showed the transport as the bottleneck: every LIST page, watch
round, events fetch and cordon PATCH paid a fresh TCP(+TLS) handshake.
These tests pin the pooled ``_StdlibSession`` replacement:

* an N-page paged LIST reuses ONE connection (the fixture server counts
  accepted connections — ground truth, not client bookkeeping);
* a keep-alive socket the server quietly closed redials exactly once on an
  idempotent GET, and the redial's failure PROPAGATES (no retry loop);
* PATCH is never blind-retried after a socket death (it may have applied);
* the security posture survives the rewrite: redirects refused,
  Authorization never re-sent, plain-http never loads the CA store;
* the per-node fan-outs (``--node-events``, cordon) complete in
  ~max(single call), not sum, with deterministic result ordering.
"""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler

import pytest

from tests import fixtures as fx
from tpu_node_checker import checker, cli, cluster
from tpu_node_checker.utils.fanout import bounded_map


def args_for(*argv):
    return cli.parse_args(list(argv))


class TestPoolReuse:
    def test_eleven_page_list_reuses_one_connection(self):
        # 110 nodes / page_limit 10 = 11 pages — the 5k-node walk's shape.
        nodes = fx.cpu_only_cluster(110)
        seen: list = []
        server = fx.serve_http(fx.paged_nodelist_handler(nodes, seen))
        try:
            cfg = cluster.ClusterConfig(
                server=f"http://127.0.0.1:{server.server_address[1]}"
            )
            client = cluster.KubeClient(cfg)
            got = client.list_nodes(page_limit=10)
            assert len(got) == 110
            assert len(seen) == 11
            assert server.connections_opened == 1  # one dial, 11 requests
            stats = client.transport_stats()
            assert stats["connections_opened"] == 1
            assert stats["requests_sent"] == 11
            assert stats["requests_reused"] == 10
            client.close()
        finally:
            server.shutdown()

    def test_keep_alive_disabled_dials_per_request(self):
        # The "before" behavior, kept dialable for the bench's honest
        # comparison: keep_alive=False pays one connection per page.
        nodes = fx.cpu_only_cluster(50)
        server = fx.serve_http(fx.paged_nodelist_handler(nodes))
        try:
            cfg = cluster.ClusterConfig(
                server=f"http://127.0.0.1:{server.server_address[1]}"
            )
            session = cluster._StdlibSession(keep_alive=False)
            client = cluster.KubeClient(cfg, session=session)
            got = client.list_nodes(page_limit=10)
            assert len(got) == 50
            assert server.connections_opened == 5
            assert session.requests_reused == 0
        finally:
            server.shutdown()

    def test_eleven_page_https_list_reuses_one_connection(self, tmp_path):
        # The acceptance shape: an 11-page HTTPS paged LIST opens exactly
        # one connection — the handshake is paid once, not per page.
        tls = fx.self_signed_cert(str(tmp_path))
        if tls is None:
            pytest.skip("openssl CLI unavailable")
        nodes = fx.cpu_only_cluster(110)
        server = fx.serve_http(fx.paged_nodelist_handler(nodes), tls_cert=tls)
        try:
            cfg = cluster.ClusterConfig(
                server=f"https://127.0.0.1:{server.server_address[1]}",
                ca_file=tls[0],
            )
            client = cluster.KubeClient(cfg)
            got = client.list_nodes(page_limit=10)
            assert len(got) == 110
            assert server.connections_opened == 1
            client.close()
        finally:
            server.shutdown()

    def test_sequential_requests_share_the_connection(self):
        # LIST + events + PATCH — the full round's call mix on one socket.
        state = {"requests": 0}

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _respond(self):
                state["requests"] += 1
                body = b'{"items": []}'
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                self._respond()

            def do_PATCH(self):
                self.rfile.read(int(self.headers.get("Content-Length", 0)))
                self._respond()

            def log_message(self, *args):
                pass

        server = fx.serve_http(Handler)
        try:
            base = f"http://127.0.0.1:{server.server_address[1]}"
            s = cluster._StdlibSession()
            s.get(f"{base}/api/v1/nodes", timeout=5).raise_for_status()
            s.get(f"{base}/api/v1/events", timeout=5).raise_for_status()
            s.patch(f"{base}/api/v1/nodes/n", data="{}", timeout=5).raise_for_status()
            assert state["requests"] == 3
            assert server.connections_opened == 1
        finally:
            server.shutdown()


class _SilentCloseState:
    """Server behavior knobs shared with the handler class."""

    def __init__(self, respond_max=None, delay_s=0.0):
        self.responses = 0
        self.respond_max = respond_max  # None = always respond
        self.delay_s = delay_s
        self.seen: list = []  # methods that ARRIVED at the server


def _silent_close_handler(state):
    """Responds, then silently closes the connection (NO Connection: close
    header) — the stale-keep-alive-socket shape an idle-timeouted API
    server LB produces.  After ``respond_max`` responses, closes every
    connection without responding at all."""

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def _serve(self):
            state.seen.append(self.command)
            if state.respond_max is not None and state.responses >= state.respond_max:
                self.close_connection = True  # slam shut, no response
                return
            time.sleep(state.delay_s)  # tnc: allow-test-wall-clock(a REAL http.server fixture: the delay forces request overlap on real sockets, which no fake clock can schedule)
            state.responses += 1
            body = b'{"items": []}'
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            # Close WITHOUT advertising it: the client pools the socket
            # and only discovers the death at its next acquire (liveness
            # peek) or, in the peek-vs-close race, on the request itself.
            self.close_connection = True

        def do_GET(self):
            self._serve()

        def do_PATCH(self):
            self.rfile.read(int(self.headers.get("Content-Length", 0)))
            self._serve()

        def log_message(self, *args):
            pass

    return Handler


def _wait_pool_dead(s, retries=50):
    """Wait until every pooled socket reads as dead (the server's FIN can
    land a few ms after the response bytes)."""
    for _ in range(retries):
        with s._lock:
            conns = [c for idle in s._pool.values() for c in idle]
        if conns and all(cluster._StdlibSession._sock_is_dead(c) for c in conns):
            return
        time.sleep(0.01)  # tnc: allow-test-wall-clock(bounded poll for the kernel to deliver FIN on a real closed socket; no clock to fake in the TCP stack)


class TestStaleSocketRecovery:
    def test_get_survives_stale_socket_with_one_fresh_dial(self):
        state = _SilentCloseState()
        server = fx.serve_http(_silent_close_handler(state))
        try:
            base = f"http://127.0.0.1:{server.server_address[1]}"
            s = cluster._StdlibSession()
            s.get(f"{base}/x", timeout=5).raise_for_status()
            _wait_pool_dead(s)
            # The pooled socket is dead: the acquire-time liveness peek
            # discards it and the GET rides exactly one fresh dial.
            s.get(f"{base}/x", timeout=5).raise_for_status()
            assert state.responses == 2
            assert server.connections_opened == 2  # original + one redial
            assert s.connections_opened == 2
            assert s.requests_reused == 0  # the dead socket was never used
        finally:
            server.shutdown()

    def test_get_retry_when_peek_race_hands_out_dead_socket(self, monkeypatch):
        # The peek is racy: the peer can close between peek and send.  Pin
        # the in-flight retry path by blinding the peek — the GET must
        # fail on the dead pooled socket, then transparently redial ONCE.
        state = _SilentCloseState()
        server = fx.serve_http(_silent_close_handler(state))
        try:
            base = f"http://127.0.0.1:{server.server_address[1]}"
            s = cluster._StdlibSession()
            s.get(f"{base}/x", timeout=5).raise_for_status()
            _wait_pool_dead(s)
            monkeypatch.setattr(
                cluster._StdlibSession, "_sock_is_dead", staticmethod(lambda c: False)
            )
            s.get(f"{base}/x", timeout=5).raise_for_status()
            assert state.responses == 2
            assert s.connections_opened == 2
            assert s.requests_reused == 0  # the reuse attempt FAILED
        finally:
            server.shutdown()

    def test_stale_failure_flushes_poolmates_so_retry_dials_fresh(self, monkeypatch):
        # Two pooled sockets, both dead (e.g. an LB idle-timeout sweep
        # between watch rounds), peek blinded: the GET's failure on corpse
        # #1 must flush corpse #2 so the single retry reaches a FRESH dial
        # instead of exhausting itself on the next dead socket.
        state = _SilentCloseState(delay_s=0.2)
        server = fx.serve_http(_silent_close_handler(state))
        try:
            from concurrent.futures import ThreadPoolExecutor

            base = f"http://127.0.0.1:{server.server_address[1]}"
            s = cluster._StdlibSession()
            with ThreadPoolExecutor(2) as pool:  # overlap via server delay
                futs = [
                    pool.submit(lambda: s.get(f"{base}/x", timeout=5)) for _ in range(2)
                ]
                for f in futs:
                    f.result().raise_for_status()
            assert s.connections_opened == 2  # both workers dialed
            _wait_pool_dead(s)
            state.delay_s = 0.0
            monkeypatch.setattr(
                cluster._StdlibSession, "_sock_is_dead", staticmethod(lambda c: False)
            )
            s.get(f"{base}/x", timeout=5).raise_for_status()  # survives
            assert s.connections_opened == 3  # exactly one fresh dial
        finally:
            server.shutdown()

    def test_redial_failure_propagates_no_retry_loop(self):
        # Respond once ever; afterwards every connection is slammed shut.
        # The post-stale fresh dial gets one shot — its failure surfaces.
        state = _SilentCloseState(respond_max=1)
        server = fx.serve_http(_silent_close_handler(state))
        try:
            base = f"http://127.0.0.1:{server.server_address[1]}"
            s = cluster._StdlibSession()
            s.get(f"{base}/x", timeout=5).raise_for_status()
            _wait_pool_dead(s)
            with pytest.raises(Exception):
                s.get(f"{base}/x", timeout=5)
            # One original dial + exactly one more — never a third.
            assert server.connections_opened == 2
        finally:
            server.shutdown()

    def test_patch_is_never_resent_after_mid_request_socket_death(self):
        # The PATCH reaches the server once, the socket dies without a
        # response — the transport must surface the failure, never re-send
        # (the first PATCH may have been applied).
        state = _SilentCloseState(respond_max=1)
        server = fx.serve_http(_silent_close_handler(state))
        try:
            base = f"http://127.0.0.1:{server.server_address[1]}"
            s = cluster._StdlibSession()
            s.get(f"{base}/x", timeout=5).raise_for_status()
            # Deterministic: once the corpse reads dead, the acquire peek
            # discards it and the PATCH rides a fresh dial — which the
            # server reads, then slams without responding.
            _wait_pool_dead(s)
            with pytest.raises(Exception):
                s.patch(f"{base}/api/v1/nodes/n", data="{}", timeout=5)
            assert state.seen.count("PATCH") == 1  # arrived once, never again
        finally:
            server.shutdown()

    def test_patch_on_raced_dead_socket_not_retried(self, monkeypatch):
        # Peek blinded (the race window): the PATCH rides the dead pooled
        # socket, its bytes go nowhere, and the transport must NOT redial-
        # and-resend — the failure surfaces as the caller's per-node note.
        state = _SilentCloseState()
        server = fx.serve_http(_silent_close_handler(state))
        try:
            base = f"http://127.0.0.1:{server.server_address[1]}"
            s = cluster._StdlibSession()
            s.get(f"{base}/x", timeout=5).raise_for_status()  # prime the pool
            _wait_pool_dead(s)
            monkeypatch.setattr(
                cluster._StdlibSession, "_sock_is_dead", staticmethod(lambda c: False)
            )
            with pytest.raises(Exception):
                s.patch(f"{base}/api/v1/nodes/n", data="{}", timeout=5)
            assert state.seen.count("PATCH") == 0  # bytes died with the socket
            assert s.connections_opened == 1  # no redial for PATCH
        finally:
            server.shutdown()


class TestSecurityPosture:
    """Redirect-refusal and http-no-CA-load, pinned against the NEW
    transport (complementing tests/test_cluster.py's TestStdlibSession)."""

    @pytest.fixture
    def redirect_server(self):
        seen = []

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def do_GET(self):
                seen.append(
                    {"path": self.path, "auth": self.headers.get("Authorization")}
                )
                self.send_response(302)
                self.send_header("Location", "http://127.0.0.1:1/elsewhere")
                self.send_header("Content-Length", "0")
                self.end_headers()

            def log_message(self, *args):
                pass

        server = fx.serve_http(Handler)
        yield f"http://127.0.0.1:{server.server_address[1]}", seen
        server.shutdown()

    def test_redirect_refused_auth_never_crosses(self, redirect_server):
        base, seen = redirect_server
        s = cluster._StdlibSession()
        s.headers["Authorization"] = "Bearer secret"
        resp = s.get(f"{base}/redirect", timeout=5)
        assert resp.status_code == 302
        with pytest.raises(cluster.ClusterAPIError, match="HTTP 302"):
            resp.raise_for_status()
        # Exactly one request total: the 302 was never followed, so the
        # Authorization header never left for the redirect target.
        assert len(seen) == 1
        assert seen[0]["auth"] == "Bearer secret"

    def test_http_target_never_builds_tls_context(self):
        nodes = fx.cpu_only_cluster(25)
        server = fx.serve_http(fx.paged_nodelist_handler(nodes))
        try:
            cfg = cluster.ClusterConfig(
                server=f"http://127.0.0.1:{server.server_address[1]}"
            )
            client = cluster.KubeClient(cfg)
            calls = []
            session = client._session
            orig = session._context
            session._context = lambda: calls.append(1) or orig()
            client.list_nodes(page_limit=10)
            assert calls == []  # a full paged walk, zero CA-store loads
        finally:
            server.shutdown()


class TestBoundedMap:
    def test_results_in_input_order_failures_captured(self):
        def work(i):
            if i == 2:
                raise ValueError("boom-2")
            time.sleep(0.01 * (5 - i))  # later items finish FIRST  # tnc: allow-test-wall-clock(real ThreadPoolExecutor scheduling under test: staggered completion order needs real elapsed time)
            return i * 10

        out = bounded_map(work, range(5), max_workers=5)
        assert [ok for ok, _ in out] == [True, True, False, True, True]
        assert [v for ok, v in out if ok] == [0, 10, 30, 40]
        assert isinstance(out[2][1], ValueError)

    def test_serial_degenerate_matches_parallel(self):
        for workers in (1, 3):
            out = bounded_map(lambda i: i + 1, [1, 2, 3], max_workers=workers)
            assert out == [(True, 2), (True, 3), (True, 4)]
        assert bounded_map(lambda i: i, [], max_workers=4) == []


class _SlowEventsClient:
    """list_node_events stand-in with injected per-request latency."""

    def __init__(self, delay_s):
        self.delay_s = delay_s
        self.calls = []
        self._lock = threading.Lock()

    def list_node_events(self, name, timeout=None, limit=100):
        time.sleep(self.delay_s)  # tnc: allow-test-wall-clock(forces overlap across real fan-out worker threads — the parallelism speedup assertion needs real elapsed time)
        with self._lock:
            self.calls.append(name)
        return [{"type": "Warning", "reason": f"R-{name}", "message": "m",
                 "lastTimestamp": "2026-07-30T10:00:00Z"}]


class TestEventsFanOut:
    def _sick_accel(self, n=8):
        nodes = fx.tpu_v5p_64_slice(not_ready=n)
        accel, _ = checker.select_accelerator_nodes(nodes)
        return accel

    def test_eight_sick_nodes_cost_max_not_sum(self, capsys):
        delay = 0.15
        client = _SlowEventsClient(delay)
        accel = self._sick_accel(8)
        t0 = time.perf_counter()
        checker._attach_node_events(
            args_for("--node-events", "--api-concurrency", "8"), accel, client
        )
        elapsed = time.perf_counter() - t0
        assert len(client.calls) == 8
        # Serial would be >= 8 * 0.15 = 1.2 s; parallel ~0.15 s + overhead.
        assert elapsed < 4 * delay, f"fan-out took {elapsed:.2f}s — serial?"
        by_name = {n.name: n for n in accel}
        for i in range(8):
            name = f"gke-tpu-v5p-{i}"
            assert by_name[name].events[0]["reason"] == f"R-{name}"
        capsys.readouterr()

    def test_concurrency_one_is_serial_and_identical(self, capsys):
        client = _SlowEventsClient(0.0)
        accel = self._sick_accel(3)
        checker._attach_node_events(
            args_for("--node-events", "--api-concurrency", "1"), accel, client
        )
        # Serial path preserves the sickness-sorted order exactly.
        assert client.calls == [f"gke-tpu-v5p-{i}" for i in range(3)]
        capsys.readouterr()

    def test_failures_stay_per_node_and_ordered(self, capsys):
        class FlakyClient(_SlowEventsClient):
            def list_node_events(self, name, timeout=None, limit=100):
                if name.endswith("-1"):
                    raise cluster.ClusterAPIError("HTTP 403: forbidden", 403)
                return super().list_node_events(name, timeout, limit)

        client = FlakyClient(0.0)
        accel = self._sick_accel(3)
        checker._attach_node_events(
            args_for("--node-events", "--api-concurrency", "4"), accel, client
        )
        err = capsys.readouterr().err
        assert "Cannot fetch events for gke-tpu-v5p-1" in err
        by_name = {n.name: n for n in accel}
        assert by_name["gke-tpu-v5p-1"].events is None
        assert by_name["gke-tpu-v5p-0"].events and by_name["gke-tpu-v5p-2"].events

    def test_api_concurrency_flag_validation(self, capsys):
        with pytest.raises(SystemExit) as e:
            cli.parse_args(["--api-concurrency", "0"])
        assert e.value.code == 2
        capsys.readouterr()
        assert cli.parse_args(["--api-concurrency", "1"]).api_concurrency == 1


class TestClientCacheAndTelemetry:
    def test_same_resolved_config_reuses_the_client(self):
        cfg = cluster.ClusterConfig(server="https://cache-test:6443", token="t")
        a = checker._cached_client(cfg)
        b = checker._cached_client(cfg)
        assert a is b
        checker.reset_client_cache()
        c = checker._cached_client(cfg)
        assert c is not a
        checker.reset_client_cache()

    def test_inline_data_kubeconfig_yields_stable_cache_key(self, tmp_path):
        # GKE-style kubeconfigs inline credentials (*-data); materialized
        # temp files are content-addressed, so re-resolving the SAME
        # kubeconfig every watch round lands on the SAME cache key — the
        # cross-round pooling this PR exists for.  Path-per-round would
        # make the client cache miss every round, silently.
        import base64

        ca = base64.b64encode(b"POOL-CA").decode()
        kc = tmp_path / "kubeconfig"
        kc.write_text(
            "apiVersion: v1\ncurrent-context: c\n"
            "contexts:\n- name: c\n  context:\n    cluster: cl\n    user: u\n"
            "clusters:\n- name: cl\n  cluster:\n"
            "    server: https://inline-data:6443\n"
            f"    certificate-authority-data: {ca}\n"
            "users:\n- name: u\n  user:\n    token: tok\n"
        )
        cfg1 = cluster.load_kubeconfig(str(kc))
        cfg2 = cluster.load_kubeconfig(str(kc))
        assert checker._client_key(cfg1) == checker._client_key(cfg2)
        assert checker._cached_client(cfg1) is checker._cached_client(cfg2)
        checker.reset_client_cache()

    def test_watch_rounds_reuse_the_pooled_connection(self, tmp_path):
        # Two run_check rounds against one live server: round 2 must pay
        # ZERO new connections — the number every watch round after the
        # first actually pays — and the payload's transport telemetry must
        # say so.
        nodes = fx.tpu_v5e_single_host()
        server = fx.serve_http(fx.paged_nodelist_handler(nodes))
        try:
            kc = tmp_path / "kubeconfig"
            kc.write_text(
                "apiVersion: v1\ncurrent-context: c\n"
                "contexts:\n- name: c\n  context:\n    cluster: cl\n    user: u\n"
                "clusters:\n- name: cl\n  cluster:\n"
                f"    server: http://127.0.0.1:{server.server_address[1]}\n"
                "users:\n- name: u\n  user:\n    token: tok\n"
            )
            args = args_for("--kubeconfig", str(kc), "--json")
            r1 = checker.run_check(args)
            r2 = checker.run_check(args)
            assert r1.exit_code == 0 and r2.exit_code == 0
            assert server.connections_opened == 1
            t2 = r2.payload["api_transport"]
            assert t2["connections_opened"] == 1
            assert t2["requests_reused"] >= 1
        finally:
            server.shutdown()
            checker.reset_client_cache()

    def test_transport_counters_rendered_as_prometheus_counters(self):
        from tpu_node_checker.metrics import render_metrics

        result = checker.CheckResult(
            exit_code=0,
            payload={
                "total_nodes": 1, "ready_nodes": 1,
                "total_chips": 4, "ready_chips": 4,
                "nodes": [], "slices": [], "timings_ms": {"total": 1.0},
                "api_transport": {
                    "connections_opened": 1,
                    "requests_sent": 12,
                    "requests_reused": 11,
                },
            },
        )
        text = render_metrics(result)
        assert "tpu_node_checker_api_connections_opened_total 1" in text
        assert "tpu_node_checker_api_requests_total 12" in text
        assert "tpu_node_checker_api_requests_reused_total 11" in text
        assert "# TYPE tpu_node_checker_api_connections_opened_total counter" in text

    def test_requests_session_dropin_reports_no_stats(self):
        class RequestsLikeSession:
            headers: dict = {}
            verify = cert = auth = None

            def get(self, url, params=None, timeout=None):
                class R:
                    status_code = 200

                    def raise_for_status(self):
                        pass

                    def json(self):
                        return {"items": []}

                return R()

        cfg = cluster.ClusterConfig(server="https://api:6443")
        client = cluster.KubeClient(cfg, session=RequestsLikeSession())
        client.list_nodes()
        assert client.transport_stats() == {}
        client.close()  # no-op, must not raise


class TestCordonFanOut:
    def test_parallel_patches_all_land_report_deterministic(self, tmp_path):
        # 4 probe-failed nodes, concurrency 4: every PATCH lands, and the
        # report's cordoned list is in candidate order regardless of which
        # worker finished first.
        patched = []
        lock = threading.Lock()

        delay = 0.15

        class FakeClient:
            def cordon_node(self, name, timeout=None):
                time.sleep(delay)  # tnc: allow-test-wall-clock(forces overlap across real fan-out worker threads — the parallelism speedup assertion needs real elapsed time)
                with lock:
                    patched.append(name)

        nodes = [
            fx.make_node(
                f"tpu-{i}", allocatable={"google.com/tpu": "4"},
                labels={"cloud.google.com/gke-nodepool": "p"},
            )
            for i in range(4)
        ]
        accel, _ = checker.select_accelerator_nodes(nodes)
        for n in accel:
            n.probe = {"ok": False, "level": "compute", "error": "dead"}
        args = args_for(
            "--probe-results", str(tmp_path), "--cordon-failed",
            "--cordon-max", "4", "--api-concurrency", "4",
        )
        t0 = time.perf_counter()
        entry = checker._cordon_failed_nodes(args, accel, client=FakeClient())
        elapsed = time.perf_counter() - t0
        assert sorted(patched) == [f"tpu-{i}" for i in range(4)]
        assert entry["cordoned"] == [f"tpu-{i}" for i in range(4)]  # input order
        assert entry["failed"] == []
        # Serial would be >= 4 * delay; same slack policy as the events
        # fan-out test (scheduler jitter on loaded CI must not flake this).
        assert elapsed < 3 * delay, f"parallel cordon took {elapsed:.2f}s — serial?"
        assert all(n.cordoned for n in accel)
