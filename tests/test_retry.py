"""utils/retry.py: classifier, Retry-After, budget, backoff — and the
transport-level idempotency gate.

Every test here runs on an INJECTED fake clock (policy constructor seams or
the retry module's ``_sleep``/``_monotonic``/``_wall_now`` globals); the
autouse guard asserts the suite adds no real sleeps — a backoff that reaches
``time.sleep`` is a bug in the test *and* a regression risk for the suite's
runtime.
"""

import http.client
import json
import socket
import time

import pytest

from tests import fixtures as fx
from tpu_node_checker import cluster
from tpu_node_checker.utils import retry as retry_mod
from tpu_node_checker.utils.retry import (
    DEFAULT_MAX_ATTEMPTS,
    RetryBudget,
    RetryPolicy,
    classify_retriable,
    parse_retry_after,
    status_retry_reason,
)


@pytest.fixture(autouse=True)
def _no_real_sleeps(monkeypatch):
    """Wall-clock guard: retry logic must never hit the real sleep from a
    test — the module seam is replaced with a tripwire, and the whole test
    is timed (sockets and fakes are milliseconds; a leaked backoff is not).
    """
    def _trip(seconds):
        raise AssertionError(
            f"retry code reached the REAL sleep ({seconds}s) — inject a fake"
        )

    monkeypatch.setattr(retry_mod, "_sleep", _trip)
    t0 = time.perf_counter()
    yield
    elapsed = time.perf_counter() - t0
    assert elapsed < 2.0, f"retry test burned {elapsed:.2f}s of wall-clock"


class FakeClock:
    """Injected time source: sleep() advances monotonic, nothing is real."""

    def __init__(self):
        self.t = 0.0
        self.sleeps = []

    def sleep(self, seconds):
        self.sleeps.append(seconds)
        self.t += seconds

    def monotonic(self):
        return self.t


def _policy(clock, budget_s=30.0, jitter_hi=True, **kw):
    """Deterministic policy: jitter pinned to the interval's top (uniform →
    upper bound) so backoff sequences are exact."""
    return RetryPolicy(
        budget=RetryBudget(budget_s),
        sleep=clock.sleep,
        monotonic=clock.monotonic,
        uniform=(lambda a, b: b) if jitter_hi else (lambda a, b: a),
        **kw,
    )


class TestClassifier:
    @pytest.mark.parametrize(
        "exc,reason",
        [
            (ConnectionRefusedError(), "connect_refused"),
            (ConnectionResetError(), "connection_reset"),
            (ConnectionAbortedError(), "connection_reset"),
            (BrokenPipeError(), "connection_reset"),
            (http.client.BadStatusLine(""), "connection_reset"),
            (http.client.RemoteDisconnected(""), "connection_reset"),
            (http.client.IncompleteRead(b"x"), "connection_reset"),
            (socket.timeout(), "timeout"),
            (TimeoutError(), "timeout"),
            (cluster.ClusterAPIError("x", status_code=429), "http_429"),
            (cluster.ClusterAPIError("x", status_code=500), "http_500"),
            (cluster.ClusterAPIError("x", status_code=503), "http_503"),
        ],
    )
    def test_retriable(self, exc, reason):
        assert classify_retriable(exc) == reason

    @pytest.mark.parametrize(
        "exc",
        [
            ValueError("not transport"),
            json.JSONDecodeError("x", "y", 0),  # a proxy's HTML is config, not a blip
            cluster.ClusterAPIError("x", status_code=404),
            cluster.ClusterAPIError("x", status_code=403),
            cluster.ClusterAPIError("x", status_code=410),  # pagination owns 410
            cluster.ClusterAPIError("no status"),
            OSError("generic"),
        ],
    )
    def test_not_retriable(self, exc):
        assert classify_retriable(exc) is None

    def test_requests_style_response_status_read(self):
        # A drop-in requests.HTTPError carries status on .response, not on
        # the exception itself.
        class Resp:
            status_code = 502

        class HTTPErrorLike(Exception):
            response = Resp()

        assert classify_retriable(HTTPErrorLike()) == "http_502"

    def test_status_reason_labels(self):
        assert status_retry_reason(429) == "http_429"
        assert status_retry_reason(502) == "http_502"
        assert status_retry_reason(200) is None
        assert status_retry_reason(410) is None


class TestRetryAfter:
    def test_delta_seconds(self):
        assert parse_retry_after("7") == 7.0
        assert parse_retry_after(" 0 ") == 0.0

    def test_http_date(self):
        # Injected wall clock: 30s before the stamped date.
        now = 784111777.0 - 30.0
        assert parse_retry_after("Sun, 06 Nov 1994 08:49:37 GMT", now=now) == 30.0

    def test_past_http_date_clamps_to_zero(self):
        now = 784111777.0 + 3600.0
        assert parse_retry_after("Sun, 06 Nov 1994 08:49:37 GMT", now=now) == 0.0

    @pytest.mark.parametrize("raw", [None, "", "soon", "12.5.3", "garbage GMT"])
    def test_unparseable_degrades_to_none(self, raw):
        assert parse_retry_after(raw, now=0.0) is None


class TestRetryBudget:
    def test_grant_clips_and_exhausts(self):
        b = RetryBudget(1.0)
        assert b.grant(0.4) == 0.4
        assert b.grant(10.0) == pytest.approx(0.6)  # clipped to what remains
        assert b.exhausted
        assert b.grant(0.1) == 0.0  # nothing left, caller must stop

    def test_charge_counts_attempt_cost(self):
        b = RetryBudget(2.0)
        b.charge(1.5)  # a failed re-attempt's wall-clock
        assert b.remaining == pytest.approx(0.5)
        b.charge(1.0)
        assert b.exhausted

    def test_zero_budget_grants_nothing(self):
        b = RetryBudget(0.0)
        assert b.exhausted
        assert b.grant(0.1) == 0.0


class TestBackoffPolicy:
    def test_full_jitter_exponential_sequence_capped(self):
        clock = FakeClock()
        # Jitter pinned to the ceiling: 0.1, 0.2, 0.4, then the 0.5 cap.
        p = RetryPolicy(
            budget=RetryBudget(30.0), max_attempts=6,
            sleep=clock.sleep, monotonic=clock.monotonic,
            uniform=lambda a, b: b, max_delay_s=0.5,
        )
        delays = [p.plan_retry(i, "http_500") for i in range(5)]
        assert delays == [0.1, 0.2, 0.4, 0.5, 0.5]

    def test_jitter_floor_is_zero(self):
        clock = FakeClock()
        p = _policy(clock, jitter_hi=False)  # uniform → lower bound
        assert p.plan_retry(0, "http_500") == 0.0  # full jitter reaches 0

    def test_attempt_cap_ends_the_sequence(self):
        clock = FakeClock()
        p = _policy(clock)
        assert p.plan_retry(DEFAULT_MAX_ATTEMPTS - 1, "http_500") is None

    def test_budget_exhaustion_ends_the_sequence(self):
        clock = FakeClock()
        p = _policy(clock, budget_s=0.15)
        assert p.plan_retry(0, "http_500") == 0.1
        # Remaining 0.05 < the 0.2 ask: granted what's left, then dry.
        assert p.plan_retry(1, "http_500") == pytest.approx(0.05)
        assert p.plan_retry(2, "http_500") is None

    def test_retry_after_sets_the_floor(self):
        clock = FakeClock()
        p = _policy(clock)
        # Backoff ceiling for attempt 0 is 0.1; the server said 1s — obey.
        assert p.plan_retry(0, "http_429", retry_after=1.0) == 1.0

    def test_unhonorable_retry_after_stops_retrying(self):
        clock = FakeClock()
        p = _policy(clock, budget_s=0.5)
        # The server demands 60s; the budget cannot honor it — fail NOW
        # rather than sleep less and re-trip the throttle.
        assert p.plan_retry(0, "http_429", retry_after=60.0) is None

    def test_wait_uses_injected_sleep_only(self):
        clock = FakeClock()
        p = _policy(clock)
        p.wait(1.25)
        assert clock.sleeps == [1.25]
        assert clock.t == 1.25


class _CountingSession(cluster._StdlibSession):
    """Stdlib session whose _attempt is scripted: raises/returns from a
    queue, counting attempts — the retry loop tested without sockets."""

    def __init__(self, script):
        super().__init__()
        self.script = list(script)
        self.attempts = 0

    def _attempt(self, method, key, path, body, hdrs, timeout, url):
        self.attempts += 1
        item = self.script.pop(0)
        if isinstance(item, BaseException):
            raise item
        return item


def _resp(status, headers=None):
    return cluster._Response(status, b"{}", "http://x/", headers=headers or {})


class TestTransportRetryLoop:
    def _session(self, script, clock, budget_s=30.0, **kw):
        s = _CountingSession(script)
        s.retry_policy = RetryPolicy(
            budget=RetryBudget(budget_s), sleep=clock.sleep,
            monotonic=clock.monotonic, uniform=lambda a, b: b, **kw,
        )
        return s

    def test_get_retries_transient_exception_then_succeeds(self):
        clock = FakeClock()
        s = self._session([ConnectionResetError(), _resp(200)], clock)
        assert s.get("http://h/x", timeout=5).status_code == 200
        assert s.attempts == 2
        assert s.retries == 1
        assert s.retries_by_reason == {"connection_reset": 1}
        assert clock.sleeps == [0.1]

    def test_get_retries_5xx_status_then_succeeds(self):
        clock = FakeClock()
        s = self._session([_resp(500), _resp(503), _resp(200)], clock)
        assert s.get("http://h/x", timeout=5).status_code == 200
        assert s.retries_by_reason == {"http_500": 1, "http_503": 1}
        assert clock.sleeps == [0.1, 0.2]

    def test_429_retry_after_header_honored(self):
        clock = FakeClock()
        s = self._session(
            [_resp(429, {"retry-after": "3"}), _resp(200)], clock
        )
        assert s.get("http://h/x", timeout=5).status_code == 200
        assert clock.sleeps == [3.0]  # server floor beats the 0.1 backoff

    def test_attempts_exhausted_returns_last_response(self):
        clock = FakeClock()
        s = self._session([_resp(500)] * DEFAULT_MAX_ATTEMPTS, clock)
        resp = s.get("http://h/x", timeout=5)
        assert resp.status_code == 500  # surfaces through raise_for_status
        assert s.attempts == DEFAULT_MAX_ATTEMPTS
        with pytest.raises(cluster.ClusterAPIError):
            resp.raise_for_status()

    def test_exception_after_attempts_exhausted_propagates(self):
        clock = FakeClock()
        s = self._session([ConnectionResetError()] * DEFAULT_MAX_ATTEMPTS, clock)
        with pytest.raises(ConnectionResetError):
            s.get("http://h/x", timeout=5)
        assert s.attempts == DEFAULT_MAX_ATTEMPTS

    def test_non_retriable_error_raises_immediately(self):
        clock = FakeClock()
        s = self._session([ValueError("boom"), _resp(200)], clock)
        with pytest.raises(ValueError):
            s.get("http://h/x", timeout=5)
        assert s.attempts == 1
        assert s.retries == 0

    def test_non_retriable_status_returns_immediately(self):
        clock = FakeClock()
        s = self._session([_resp(404), _resp(200)], clock)
        assert s.get("http://h/x", timeout=5).status_code == 404
        assert s.attempts == 1

    def test_patch_never_retried_on_sent_request_failure(self):
        # The socket died AFTER the request may have left: re-sending could
        # double-apply — the error surfaces, attempt count stays 1.
        clock = FakeClock()
        s = self._session([ConnectionResetError(), _resp(200)], clock)
        with pytest.raises(ConnectionResetError):
            s.patch("http://h/x", data="{}", timeout=5)
        assert s.attempts == 1
        assert s.retries == 0

    def test_patch_5xx_response_not_retried(self):
        # A 500 to a PATCH is ambiguous (may have half-applied): strict
        # gating returns it to the caller, never re-sends.
        clock = FakeClock()
        s = self._session([_resp(500), _resp(200)], clock)
        assert s.patch("http://h/x", data="{}", timeout=5).status_code == 500
        assert s.attempts == 1

    def test_patch_retried_when_provably_never_sent(self):
        clock = FakeClock()
        exc = ConnectionRefusedError()
        exc.request_never_sent = True  # the transport's connect-phase tag
        s = self._session([exc, _resp(200)], clock)
        assert s.patch("http://h/x", data="{}", timeout=5).status_code == 200
        assert s.retries_by_reason == {"connect_refused": 1}

    def test_timeout_attempt_cost_charged_to_budget(self):
        # Each failed attempt's wall-clock counts as retry overhead: a
        # server that eats a 5s timeout per attempt exhausts an 8s budget
        # after ONE retry — never four.
        clock = FakeClock()

        class TimeoutScript(_CountingSession):
            def _attempt(self, *a, **kw):
                self.attempts += 1
                clock.t += 5.0  # the attempt itself burned 5s
                raise socket.timeout()

        s = TimeoutScript([])
        s.retry_policy = RetryPolicy(
            budget=RetryBudget(8.0), sleep=clock.sleep,
            monotonic=clock.monotonic, uniform=lambda a, b: b,
        )
        with pytest.raises(socket.timeout):
            s.get("http://h/x", timeout=5)
        # Attempt 1 fails (5s charged) → retry; attempt 2 fails (10s total
        # charged > 8s budget) → budget dry, no third attempt.
        assert s.attempts == 2

    def test_slow_error_response_cost_charged_to_budget(self):
        # Same invariant on the STATUS path: a 500 the server took 5s to
        # emit is retry overhead too — an 8s budget allows one retry, not
        # a full attempt-cap's worth of 5s failures.
        clock = FakeClock()

        class SlowErrorScript(_CountingSession):
            def _attempt(self, *a, **kw):
                self.attempts += 1
                clock.t += 5.0  # the server dribbled the error out slowly
                return _resp(500)

        s = SlowErrorScript([])
        s.retry_policy = RetryPolicy(
            budget=RetryBudget(8.0), sleep=clock.sleep,
            monotonic=clock.monotonic, uniform=lambda a, b: b,
        )
        resp = s.get("http://h/x", timeout=5)
        assert resp.status_code == 500
        assert s.attempts == 2  # budget (10s charged > 8s), not the cap (4)

    def test_no_policy_means_no_retry_no_overhead(self):
        s = _CountingSession([ConnectionResetError(), _resp(200)])
        assert s.retry_policy is None
        with pytest.raises(ConnectionResetError):
            s.get("http://h/x", timeout=5)
        assert s.attempts == 1


class TestSharedBudgetAcrossWorkers:
    def test_fanout_workers_draw_from_one_budget(self):
        # Two "workers" (sequential here; the budget is the shared object)
        # against a budget that covers only the first one's retries: the
        # second stops immediately instead of doubling the round's worst
        # case — a retrying worker can't hold its pool slot past the budget.
        clock = FakeClock()
        budget = RetryBudget(0.1)
        policy = RetryPolicy(
            budget=budget, sleep=clock.sleep, monotonic=clock.monotonic,
            uniform=lambda a, b: b,
        )
        first = _CountingSession([ConnectionResetError(), _resp(200)])
        second = _CountingSession([ConnectionResetError(), _resp(200)])
        first.retry_policy = policy
        second.retry_policy = policy
        assert first.get("http://h/x", timeout=5).status_code == 200
        assert budget.exhausted
        with pytest.raises(ConnectionResetError):
            second.get("http://h/x", timeout=5)
        assert second.attempts == 1


class TestPatchNonDuplicationServerSide:
    """Satellite: under injected mid-request connection drops, a cordon
    PATCH is never sent twice — counted on the SERVER side."""

    def test_cordon_patch_arrives_exactly_once_on_mid_request_drop(self):
        patches = []
        # First PATCH: received, then the socket is slammed with no
        # response.  The trap: if the client (wrongly) re-sent, the second
        # request would get "ok" and patches would count 2.
        schedule = fx.FaultSchedule(["reset"], then="ok")
        srv = fx.serve_http(
            fx.fault_scheduled_handler([], schedule, patches_seen=patches)
        )
        try:
            cfg = cluster.ClusterConfig(
                server=f"http://127.0.0.1:{srv.server_address[1]}"
            )
            client = cluster.KubeClient(cfg)
            clock = FakeClock()
            client.set_retry_policy(
                RetryPolicy(
                    budget=RetryBudget(30.0), sleep=clock.sleep,
                    monotonic=clock.monotonic,
                )
            )
            with pytest.raises(Exception):
                client.cordon_node("tpu-0", timeout=5)
            assert len(patches) == 1  # arrived once, NEVER re-sent
            assert client.transport_stats()["retries"] == 0
            client.close()
        finally:
            srv.shutdown()

    def test_patch_connect_refused_is_retried_never_duplicated(self):
        # Nothing listens on the port: every connect is refused before any
        # byte leaves the socket — the ONE PATCH failure mode that is
        # safely retriable, and the transport tags it as provably unsent.
        sock = socket.socket()
        sock.bind(("127.0.0.1", 0))
        port = sock.getsockname()[1]
        sock.close()  # freed: connects now refuse
        cfg = cluster.ClusterConfig(server=f"http://127.0.0.1:{port}")
        client = cluster.KubeClient(cfg)
        clock = FakeClock()
        client.set_retry_policy(
            RetryPolicy(
                budget=RetryBudget(30.0), sleep=clock.sleep,
                monotonic=clock.monotonic,
            )
        )
        with pytest.raises(ConnectionRefusedError):
            client.cordon_node("tpu-0", timeout=5)
        stats = client.transport_stats()
        assert stats["retries"] == DEFAULT_MAX_ATTEMPTS - 1
        assert stats["retries_by_reason"] == {
            "connect_refused": DEFAULT_MAX_ATTEMPTS - 1
        }
        client.close()

    def test_get_recovers_through_fail_two_then_succeed_schedule(self):
        # fail-N-then-succeed: the canonical transient blip, server-side
        # request count pinned (3 = two faults + the success).  The reset
        # comes FIRST (fresh connection) so it exercises the retry layer —
        # a reset on a reused keep-alive socket is absorbed by the
        # transport's own stale-socket redial instead, costing no budget.
        schedule = fx.FaultSchedule(["reset", "500"])
        srv = fx.serve_http(
            fx.fault_scheduled_handler(fx.cpu_only_cluster(3), schedule)
        )
        try:
            cfg = cluster.ClusterConfig(
                server=f"http://127.0.0.1:{srv.server_address[1]}"
            )
            client = cluster.KubeClient(cfg)
            clock = FakeClock()
            client.set_retry_policy(
                RetryPolicy(
                    budget=RetryBudget(30.0), sleep=clock.sleep,
                    monotonic=clock.monotonic,
                )
            )
            nodes = client.list_nodes(timeout=5)
            assert len(nodes) == 3
            assert schedule.served == ["reset", "500", "ok"]
            assert client.transport_stats()["retries"] == 2
            client.close()
        finally:
            srv.shutdown()
