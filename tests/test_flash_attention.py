"""Pallas flash-attention kernel tests (interpret mode on the CPU mesh).

The Mosaic compile path itself only exists on real TPU hardware; these tests
pin the kernel's algorithm — online softmax, block scheduling, causal
structure — which is identical in interpret mode."""

import jax
import jax.numpy as jnp
import numpy as np

from tpu_node_checker.ops import flash_attention, flash_attention_probe
from tpu_node_checker.ops.flash_attention import BLOCK, _xla_causal_attention


class TestFlashAttentionProbe:
    def test_matches_xla(self):
        r = flash_attention_probe(seq=256)
        assert r.ok, r.error
        assert r.interpreted is True  # CPU mesh → interpret mode
        assert r.max_abs_err < 2e-2

    def test_invalid_seq_is_usage_error(self):
        r = flash_attention_probe(seq=100)
        assert not r.ok
        assert "multiple of" in r.error

    def test_probe_never_raises(self):
        r = flash_attention_probe(seq=256, head_dim=0)
        assert not r.ok
        assert r.error

    def test_invalid_dims_degrade_without_warnings(self, recwarn):
        # head_dim=0 must be rejected up front — not leak a numpy
        # divide-by-zero RuntimeWarning from 1/sqrt(0) before failing
        # (VERDICT r01 item #9).
        for kwargs in ({"head_dim": 0}, {"head_dim": -8}, {"batch": 0},
                       {"heads": 0}, {"seq": 0}, {"seq": -128}):
            r = flash_attention_probe(seq=256, **kwargs) if "seq" not in kwargs \
                else flash_attention_probe(**kwargs)
            assert not r.ok
            assert "invalid" in r.error
        assert [w for w in recwarn.list if issubclass(w.category, RuntimeWarning)] == []


class TestFlashAttentionKernel:
    def _qkv(self, seed=0, B=1, H=2, S=256, D=64, dtype=jnp.float32):
        ks = jax.random.split(jax.random.PRNGKey(seed), 3)
        return tuple(jax.random.normal(k, (B, H, S, D), dtype) for k in ks)

    def test_f32_tight_match(self):
        q, k, v = self._qkv()
        out = flash_attention(q, k, v, interpret=True)
        ref = _xla_causal_attention(q, k, v)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5
        )

    def test_causality(self):
        # Query block 0 must be blind to K/V beyond the first block.
        q, k, v = self._qkv(seed=1)
        out_a = flash_attention(q, k, v, interpret=True)
        k2 = k.at[:, :, BLOCK:].set(0.0)
        v2 = v.at[:, :, BLOCK:].set(0.0)
        out_b = flash_attention(q, k2, v2, interpret=True)
        np.testing.assert_allclose(
            np.asarray(out_a)[:, :, :BLOCK],
            np.asarray(out_b)[:, :, :BLOCK],
            rtol=1e-5,
        )
        # ...and later blocks must NOT be blind to earlier K/V.
        assert not np.allclose(
            np.asarray(out_a)[:, :, BLOCK:], np.asarray(out_b)[:, :, BLOCK:]
        )

    def test_bf16_dtype_preserved(self):
        q, k, v = self._qkv(seed=2, dtype=jnp.bfloat16)
        out = flash_attention(q, k, v, interpret=True)
        assert out.dtype == jnp.bfloat16

    def test_gradients_match_xla(self):
        # custom_vjp: Pallas forward, XLA-recompute backward — grads must
        # equal differentiating the reference directly.
        q, k, v = self._qkv(seed=3)

        def loss_flash(q, k, v):
            return jnp.sum(jnp.tanh(flash_attention(q, k, v, True)))

        def loss_ref(q, k, v):
            return jnp.sum(jnp.tanh(_xla_causal_attention(q, k, v)))

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)
