"""Test session config.

Tests must be hermetic and never require (or occupy) real TPU hardware: force
JAX onto a virtual 8-device CPU mesh so sharding / collective tests exercise
real multi-device paths on any machine.

Two quirks of the dev image are handled explicitly:

* a ``sitecustomize`` registers the TPU PJRT plugin at interpreter start and
  force-sets ``jax_platforms`` — env vars alone don't win, so the config API
  is used after import;
* probe-subprocess tests spawn fresh interpreters, which would re-register the
  TPU plugin; dropping the trigger env var keeps the children on the CPU mesh.
"""

import os

# Any probe report our own code emits during tests is hard-checked against
# the declared schema (probe/schema.py) — drift fails the suite, not a
# production emitter.
os.environ.setdefault("TNC_SCHEMA_STRICT", "1")

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)  # children: no TPU plugin registration
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def shared_compute_probe():
    """One real, CLEAN compute-level probe child on the CPU mesh, shared by
    every test that only READS the healthy verdict (VERDICT r04 next #6:
    each probe child pays a fresh jax import — the suite was paying it tens
    of times for the same clean result).  Tests that mutate probe inputs
    (TNC_* env, flags, chaos) must spawn their own child.  The spawn runs
    with TNC_* scrubbed so no requesting test's environment can leak in.
    """
    from tpu_node_checker.probe.liveness import run_local_probe

    saved = {k: os.environ.pop(k) for k in list(os.environ) if k.startswith("TNC_")}
    try:
        r = run_local_probe(level="compute", timeout_s=400)
    finally:
        os.environ.update(saved)
    assert r.ok, r.error
    return r
