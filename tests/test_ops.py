"""Single-chip compute probe tests (runs on the CPU backend; same jitted code
paths as TPU — shapes kept small so the suite stays fast)."""

from tpu_node_checker.ops import hbm_bandwidth_probe, matmul_burn


class TestMatmulBurn:
    def test_burn_passes_on_healthy_backend(self):
        r = matmul_burn(n=256, iters=2)
        assert r.ok, r.error
        assert r.tflops > 0
        assert r.rel_err < 5e-2

    def test_result_fields(self):
        r = matmul_burn(n=128, iters=1)
        assert r.n == 128 and r.iters == 1
        assert r.elapsed_ms > 0


class TestHbmProbe:
    def test_bandwidth_positive(self):
        r = hbm_bandwidth_probe(mib=8, iters=2)
        assert r.ok, r.error
        assert r.gbps > 0
        assert r.bytes_moved == 2 * 8 * 1024 * 1024 * 2
