"""Single-chip compute probe tests (runs on the CPU backend; same jitted code
paths as TPU — shapes kept small so the suite stays fast)."""

from tpu_node_checker.ops import hbm_bandwidth_probe, matmul_burn, soak_burn


class TestMatmulBurn:
    def test_burn_passes_on_healthy_backend(self):
        r = matmul_burn(n=256, iters=2)
        assert r.ok, r.error
        assert r.tflops > 0
        assert r.rel_err < 5e-2

    def test_result_fields(self):
        r = matmul_burn(n=128, iters=1)
        assert r.n == 128 and r.iters == 1
        assert r.elapsed_ms > 0


class TestSoakBurn:
    def test_soak_runs_to_budget(self):
        # min_sustained_ratio=0: sub-ms CPU rounds make min/median pure OS
        # jitter; the throughput criterion is for seconds-scale TPU rounds.
        r = soak_burn(0.5, n=128, iters=2, min_sustained_ratio=0.0, hbm_mib=8)
        assert r.ok, r.error
        assert r.rounds >= 1
        assert r.seconds >= 0.5
        assert 0 < r.tflops_min <= r.tflops_median <= r.tflops_max
        assert r.sustained_ratio > 0
        assert 0 < r.hbm_gbps_min <= r.hbm_gbps_median  # memory leg ran too

    def test_throughput_collapse_fails(self):
        r = soak_burn(0.2, n=128, iters=1, min_sustained_ratio=1.01)
        # min is by definition ≤ median, so a >1 floor must always trip.
        assert not r.ok
        assert "sustained load" in r.error

    def test_zero_budget_still_runs_one_round(self):
        r = soak_burn(0.0, n=128, iters=1)
        assert r.rounds == 1

    def test_to_dict_serializes(self):
        import json

        r = soak_burn(0.1, n=128, iters=1)
        doc = json.loads(json.dumps(r.to_dict()))
        assert doc["rounds"] == r.rounds
        assert "tflops_median" in doc


class TestPallasProbe:
    def test_interpreted_matmul_matches_xla(self):
        from tpu_node_checker.ops import pallas_matmul_probe

        r = pallas_matmul_probe(m=256, k=256, n=256)
        assert r.ok, r.error
        assert r.interpreted  # CPU backend → interpreter mode
        assert r.max_rel_err < 2e-2

    def test_non_tile_shape_rejected_cleanly(self):
        from tpu_node_checker.ops import pallas_matmul_probe

        r = pallas_matmul_probe(m=100, k=100, n=100)  # not tile-divisible
        assert not r.ok
        assert "invalid shape" in r.error  # usage error, not a chip fault

    def test_zero_and_negative_dims_rejected(self):
        # 0 IS a multiple of 128 — the positivity check must catch it.
        from tpu_node_checker.ops import pallas_matmul_probe

        for kwargs in ({"m": 0}, {"k": -128}, {"n": 0}):
            r = pallas_matmul_probe(**{"m": 256, "k": 256, "n": 256, **kwargs})
            assert not r.ok
            assert "invalid shape" in r.error


class TestDmaProbe:
    def test_double_buffered_stream_matches(self):
        from tpu_node_checker.ops import dma_stream_probe

        r = dma_stream_probe(rows=512, cols=128, chunk_rows=128)
        assert r.ok, r.error
        assert r.interpreted  # CPU backend → interpreter mode
        assert r.gbps > 0

    def test_single_chunk_edge(self):
        from tpu_node_checker.ops import dma_stream_probe

        r = dma_stream_probe(rows=128, cols=128, chunk_rows=128)
        assert r.ok, r.error

    def test_bad_chunking_rejected(self):
        from tpu_node_checker.ops import dma_stream_probe

        r = dma_stream_probe(rows=100, chunk_rows=64)
        assert not r.ok
        assert "multiple of chunk_rows" in r.error

    def test_zero_dims_rejected(self):
        from tpu_node_checker.ops import dma_stream_probe

        for kwargs in ({"rows": 0}, {"cols": 0}, {"chunk_rows": 0}):
            r = dma_stream_probe(**{"rows": 128, "cols": 128, "chunk_rows": 128,
                                    **kwargs})
            assert not r.ok
            assert "invalid shape" in r.error


class TestInt8Probe:
    def test_exact_integer_match(self):
        from tpu_node_checker.ops import int8_matmul_probe

        r = int8_matmul_probe(m=128, k=128, n=128)
        assert r.ok, r.error
        assert r.tops >= 0
        assert r.elapsed_ms > 0

    def test_invalid_dims_rejected(self):
        from tpu_node_checker.ops import int8_matmul_probe

        for kwargs in ({"m": 0}, {"k": -1}, {"n": 0}):
            r = int8_matmul_probe(**{"m": 128, "k": 128, "n": 128, **kwargs})
            assert not r.ok
            assert "invalid shape" in r.error

    def test_accumulator_cannot_wrap(self):
        # Inputs are [-8, 7], so max |product| = 64 (−8·−8) and the chained
        # accumulator is bounded by iters·k·64.  Read the PROBE'S OWN
        # defaults so bumping k/iters without rethinking the bound fails
        # here instead of silently eroding the exactness guarantee.
        import inspect

        from tpu_node_checker.ops import int8_matmul_probe

        sig = inspect.signature(int8_matmul_probe)
        k = sig.parameters["k"].default
        iters = sig.parameters["iters"].default
        assert iters * k * 64 < 2**31 // 8  # 8x headroom, not just no-wrap


class TestHbmProbe:
    def test_bandwidth_positive(self):
        r = hbm_bandwidth_probe(mib=8, iters=2)
        assert r.ok, r.error
        assert r.gbps > 0
        assert r.bytes_moved == 2 * 8 * 1024 * 1024 * 2

    def test_invalid_args_rejected(self):
        for kwargs in ({"mib": 0}, {"mib": -1}, {"iters": 0}):
            r = hbm_bandwidth_probe(**{"mib": 8, "iters": 2, **kwargs})
            assert not r.ok
            assert "invalid args" in r.error


class TestMemtestProbe:
    def test_patterns_clean_on_healthy_memory(self):
        from tpu_node_checker.ops import hbm_pattern_probe

        r = hbm_pattern_probe(mib=4, dwell_s=0.05)
        assert r.ok, r.error
        assert set(r.mismatches) == {"0x55", "0xAA", "addr"}
        assert all(v == 0 for v in r.mismatches.values())
        assert r.elapsed_ms > 0

    def test_to_dict_serializes(self):
        import json

        from tpu_node_checker.ops import hbm_pattern_probe

        r = hbm_pattern_probe(mib=1, dwell_s=0.0)
        json.dumps(r.to_dict())

    def test_invalid_args_rejected(self):
        from tpu_node_checker.ops import hbm_pattern_probe

        assert not hbm_pattern_probe(mib=0).ok
        assert not hbm_pattern_probe(mib=1, dwell_s=-1).ok

    def test_corruption_is_counted_exactly(self):
        # Flip 3 words of a written buffer and verify the count is exactly 3 —
        # the probe's verdict must be word-precise, not approximate.
        import jax.numpy as jnp

        from tpu_node_checker.ops import memtest

        n = (1 * 1024 * 1024) // 4
        buf = memtest._write("addr", n)
        corrupted = buf.at[jnp.array([0, 1234, n - 1])].set(jnp.uint32(0xDEADBEEF))
        assert int(memtest._verify("addr", corrupted)) == 3

    def test_addr_pattern_detects_aliasing(self):
        # A rolled buffer models a decoder fault (every word read from the
        # wrong address): the constant patterns CANNOT see it, addr must.
        import jax.numpy as jnp

        from tpu_node_checker.ops import memtest

        n = 4096
        rolled = jnp.roll(memtest._write("addr", n), 1)
        assert int(memtest._verify("addr", rolled)) > 0
        const_rolled = jnp.roll(memtest._write("0x55", n), 1)
        assert int(memtest._verify("0x55", const_rolled)) == 0  # blind, by design
