"""Single-chip compute probe tests (runs on the CPU backend; same jitted code
paths as TPU — shapes kept small so the suite stays fast)."""

from tpu_node_checker.ops import hbm_bandwidth_probe, matmul_burn


class TestMatmulBurn:
    def test_burn_passes_on_healthy_backend(self):
        r = matmul_burn(n=256, iters=2)
        assert r.ok, r.error
        assert r.tflops > 0
        assert r.rel_err < 5e-2

    def test_result_fields(self):
        r = matmul_burn(n=128, iters=1)
        assert r.n == 128 and r.iters == 1
        assert r.elapsed_ms > 0


class TestPallasProbe:
    def test_interpreted_matmul_matches_xla(self):
        from tpu_node_checker.ops import pallas_matmul_probe

        r = pallas_matmul_probe(m=256, k=256, n=256)
        assert r.ok, r.error
        assert r.interpreted  # CPU backend → interpreter mode
        assert r.max_rel_err < 2e-2

    def test_non_tile_shape_rejected_cleanly(self):
        from tpu_node_checker.ops import pallas_matmul_probe

        r = pallas_matmul_probe(m=100, k=100, n=100)  # not tile-divisible
        assert not r.ok
        assert "invalid shape" in r.error  # usage error, not a chip fault


class TestDmaProbe:
    def test_double_buffered_stream_matches(self):
        from tpu_node_checker.ops import dma_stream_probe

        r = dma_stream_probe(rows=512, cols=128, chunk_rows=128)
        assert r.ok, r.error
        assert r.interpreted  # CPU backend → interpreter mode
        assert r.gbps > 0

    def test_single_chunk_edge(self):
        from tpu_node_checker.ops import dma_stream_probe

        r = dma_stream_probe(rows=128, cols=128, chunk_rows=128)
        assert r.ok, r.error

    def test_bad_chunking_rejected(self):
        from tpu_node_checker.ops import dma_stream_probe

        r = dma_stream_probe(rows=100, chunk_rows=64)
        assert not r.ok
        assert "multiple of chunk_rows" in r.error


class TestHbmProbe:
    def test_bandwidth_positive(self):
        r = hbm_bandwidth_probe(mib=8, iters=2)
        assert r.ok, r.error
        assert r.gbps > 0
        assert r.bytes_moved == 2 * 8 * 1024 * 1024 * 2
