"""The DEGRADED evidence class, end to end.

The mesh link doctor (--probe-level mesh) grades a node whose chips pass
but whose ICI link is SLOW as DEGRADED — an evidence VERDICT between the
booleans, never an FSM state.  These tests pin the three contracts the
class rides on:

* FSM: a degraded round must not bank toward --cordon-after as if
  FAILED, must not reset a SUSPECT streak as if healthy, must not enter
  the flap window — but unlike no-evidence it DOES mint a machine;
* store: ``"ok": "degraded"`` lines round-trip the tail-seed (the flap
  replay skips them like any non-bool verdict);
* remediation: --cordon-degraded drains the sick-link slice through the
  budget engine's decide() under the same rails as --cordon-failed,
  while the no-flag run's exit code and actuation stay untouched.
"""

import json
import time
from http.server import BaseHTTPRequestHandler

import pytest

from tests import fixtures as fx
from tpu_node_checker import checker, cli
from tpu_node_checker.history import (
    DEGRADED,
    FAILED,
    HEALTHY,
    SUSPECT,
    HealthFSM,
    HistoryStore,
)


class TestDegradedVerdictFSM:
    def test_degraded_never_banks_toward_cordon_after(self):
        fsm = HealthFSM(cordon_after=2)
        fsm.observe("n", False)  # SUSPECT, streak 1
        fsm.observe("n", DEGRADED)  # must NOT count as the 2nd bad round
        h = fsm.health("n")
        assert h.state == SUSPECT and h.streak == 1
        fsm.observe("n", DEGRADED)
        assert fsm.health("n").state == SUSPECT
        assert not fsm.cordon_eligible("n")
        fsm.observe("n", False)  # the REAL 2nd bad round condemns
        assert fsm.health("n").state == FAILED

    def test_degraded_never_resets_suspect_streak(self):
        fsm = HealthFSM(cordon_after=3)
        fsm.observe("n", False)
        fsm.observe("n", False)
        assert fsm.health("n").streak == 2
        fsm.observe("n", DEGRADED)  # not a healthy round either
        h = fsm.health("n")
        assert h.state == SUSPECT and h.streak == 2

    def test_degraded_never_enters_flap_window(self):
        fsm = HealthFSM()
        fsm.observe("n", True)
        for _ in range(6):
            # SLOW<->OK link weather interleaved with good rounds must
            # not read as verdict flips.
            fsm.observe("n", DEGRADED)
            fsm.observe("n", True)
        h = fsm.health("n")
        assert h.flaps == 0 and h.flaps_total == 0
        assert h.state == HEALTHY

    def test_degraded_mints_a_machine_unlike_none(self):
        fsm = HealthFSM()
        assert fsm.observe("ghost", None) is None
        assert "ghost" not in fsm.nodes  # absence observes nothing
        fsm.observe("sick-link", DEGRADED)
        # Affirmative evidence: the node exists and computes.
        assert "sick-link" in fsm.nodes
        assert fsm.health("sick-link").state == HEALTHY

    def test_degraded_holds_recovering_quarantine(self):
        fsm = HealthFSM(cordon_after=1, uncordon_after=2)
        fsm.observe("n", False)  # FAILED
        fsm.observe("n", True)  # RECOVERING, streak 1
        fsm.observe("n", DEGRADED)  # must not bank the 2nd good round
        assert not fsm.uncordon_eligible("n")
        fsm.observe("n", True)
        assert fsm.uncordon_eligible("n")


class TestDegradedStoreRoundTrip:
    def test_degraded_lines_round_trip_tail_seed(self, tmp_path):
        path = tmp_path / "h.jsonl"
        store = HistoryStore(str(path))
        for ok in (True, DEGRADED, False, DEGRADED):
            store.record(
                {"node": "n", "ts": 1.0, "ok": ok, "causes": [],
                 "state": SUSPECT, "streak": 1, "flaps": 0,
                 "flaps_total": 0}
            )
        store.flush()
        reloaded = HistoryStore(str(path)).load()
        assert [e["ok"] for e in reloaded["n"]] == [True, "degraded",
                                                   False, "degraded"]
        fsm = HealthFSM()
        fsm.seed("n", reloaded["n"])
        h = fsm.health("n")
        # Only the two BOOL verdicts replay into the flap window.
        assert list(h.verdicts) == [True, False]
        assert h.state == SUSPECT and h.streak == 1


def _nodes_json(tmp_path, nodes):
    p = tmp_path / "nodes.json"
    p.write_text(json.dumps(fx.node_list(nodes)))
    return str(p)


def _tpu_nodes(n=2):
    return [
        fx.make_node(
            f"tpu-{i}",
            allocatable={"google.com/tpu": "4"},
            labels={
                "cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice",
                "cloud.google.com/gke-nodepool": "p",
            },
        )
        for i in range(n)
    ]


def _links(slow=()):
    links = {}
    for name in ("t0/0", "t0/1", "t1/0", "t1/1"):
        if name in slow:
            links[name] = {"verdict": "SLOW", "p50_us": 900.0,
                           "p99_us": 950.0, "budget_us": 400.0}
        else:
            links[name] = {"verdict": "OK", "p50_us": 50.0,
                           "p99_us": 60.0, "budget_us": 400.0}
    return links


def _mesh_reports(tmp_path, degraded, name="probes"):
    """Per-host mesh-level reports; degraded = {host: [slow link names]}."""
    d = tmp_path / name
    d.mkdir(exist_ok=True)
    for host, slow in degraded.items():
        (d / f"{host}.json").write_text(
            json.dumps(
                {
                    "ok": True,
                    "level": "mesh",
                    "hostname": host,
                    "written_at": time.time(),
                    "error": None,
                    "mesh_ok": True,
                    "mesh_degraded": bool(slow),
                    "mesh_n_links": 4,
                    "mesh_latency_us": 1234.5,
                    **({"mesh_slow_links": sorted(slow)} if slow else {}),
                    "collective_legs_ok": {
                        "psum_ok": True,
                        "all_gather_ok": True,
                        "reduce_scatter_ok": True,
                        "psum_latency_us": 11.0,
                        "all_gather_latency_us": 12.0,
                        "reduce_scatter_latency_us": 13.0,
                        "links": _links(slow),
                    },
                }
            )
        )
    return str(d)


class TestDegradedThroughChecker:
    def test_degraded_round_holds_state_and_names_cause(self, tmp_path):
        nodes = _tpu_nodes(2)
        reports = _mesh_reports(
            tmp_path, {"tpu-0": ["t1/1"], "tpu-1": []}
        )
        args = cli.parse_args(
            [
                "--nodes-json", _nodes_json(tmp_path, nodes),
                "--probe-results", reports,
                "--history", str(tmp_path / "h.jsonl"),
                "--json",
            ]
        )
        res = checker.run_check(args)
        # Exit-code contract unchanged: the chips pass, the round is OK.
        assert res.exit_code == 0
        entries = HistoryStore(str(tmp_path / "h.jsonl")).load()
        sick = entries["tpu-0"][-1]
        assert sick["ok"] == "degraded"
        assert sick["causes"] == ["degraded-link"]
        assert sick["state"] == HEALTHY  # held, not sickened
        assert entries["tpu-1"][-1]["ok"] is True

    def test_degraded_evidence_rides_budget_view(self, tmp_path):
        nodes = _tpu_nodes(2)
        reports = _mesh_reports(tmp_path, {"tpu-0": ["t1/1"], "tpu-1": []})
        args = cli.parse_args(
            [
                "--nodes-json", _nodes_json(tmp_path, nodes),
                "--probe-results", reports,
                "--cordon-degraded", "--cordon-dry-run",
                "--json",
            ]
        )
        res = checker.run_check(args)
        block = res.payload["remediation"]["degraded"]
        assert block["nodes"] == ["tpu-0"]
        # Slice-qualified: the budget-domain name prefixes the link.
        assert block["links"] == ["p/tpu-v5-lite-podslice/-/t1/1"]
        assert block["domains"] == ["p/tpu-v5-lite-podslice/-"]

    def test_no_flag_run_payload_untouched(self, tmp_path):
        nodes = _tpu_nodes(1)
        reports = _mesh_reports(tmp_path, {"tpu-0": ["t1/1"]})
        args = cli.parse_args(
            [
                "--nodes-json", _nodes_json(tmp_path, nodes),
                "--probe-results", reports,
                "--json",
            ]
        )
        res = checker.run_check(args)
        assert res.exit_code == 0
        for key in ("cordon", "cordon_degraded", "remediation"):
            assert key not in res.payload


@pytest.fixture
def fake_api(tmp_path):
    patches = []

    class Handler(BaseHTTPRequestHandler):
        def do_PATCH(self):
            body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
            patches.append({"path": self.path, "body": json.loads(body)})
            payload = b"{}"
            self.send_response(200)
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def log_message(self, *args):
            pass

    server = fx.serve_http(Handler)
    kubeconfig = tmp_path / "kubeconfig"
    kubeconfig.write_text(
        f"""
apiVersion: v1
kind: Config
current-context: t
contexts: [{{name: t, context: {{cluster: t, user: t}}}}]
clusters: [{{name: t, cluster: {{server: "http://127.0.0.1:{server.server_address[1]}"}}}}]
users: [{{name: t, user: {{token: tok}}}}]
"""
    )
    yield {"patches": patches, "kubeconfig": str(kubeconfig)}
    server.shutdown()


class TestCordonDegraded:
    def test_dry_run_reports_without_patching(self, tmp_path, capsys):
        nodes = _tpu_nodes(2)
        reports = _mesh_reports(tmp_path, {"tpu-0": ["t1/1"], "tpu-1": []})
        args = cli.parse_args(
            [
                "--nodes-json", _nodes_json(tmp_path, nodes),
                "--probe-results", reports,
                "--cordon-degraded", "--cordon-dry-run",
                "--slice-floor-pct", "10",
                "--json",
            ]
        )
        res = checker.run_check(args)
        block = res.payload["cordon_degraded"]
        assert block["dry_run"] is True
        assert block["cordoned"] == ["tpu-0"]
        assert block["links"] == ["p/tpu-v5-lite-podslice/-/t1/1"]
        assert "would cordon tpu-0 (degraded ICI link)" in capsys.readouterr().err

    def test_real_patch_cordons_degraded_node(self, tmp_path, fake_api):
        nodes = _tpu_nodes(2)
        reports = _mesh_reports(tmp_path, {"tpu-0": ["t1/1"], "tpu-1": []})
        args = cli.parse_args(
            [
                "--nodes-json", _nodes_json(tmp_path, nodes),
                "--probe-results", reports,
                "--kubeconfig", fake_api["kubeconfig"],
                "--cordon-degraded", "--slice-floor-pct", "10",
                "--json",
            ]
        )
        res = checker.run_check(args)
        assert res.payload["cordon_degraded"]["cordoned"] == ["tpu-0"]
        cordons = [
            p for p in fake_api["patches"] if "tpu-0" in p["path"]
        ]
        assert cordons and cordons[0]["body"]["spec"]["unschedulable"] is True
        # The healthy-link node is never touched.
        assert not any("tpu-1" in p["path"] for p in fake_api["patches"])

    def test_cordon_max_budget_gates_the_sweep(self, tmp_path, fake_api):
        nodes = _tpu_nodes(2)
        reports = _mesh_reports(
            tmp_path, {"tpu-0": ["t1/1"], "tpu-1": ["t0/0"]}
        )
        args = cli.parse_args(
            [
                "--nodes-json", _nodes_json(tmp_path, nodes),
                "--probe-results", reports,
                "--kubeconfig", fake_api["kubeconfig"],
                "--cordon-degraded", "--cordon-max", "1",
                "--slice-floor-pct", "10",
                "--json",
            ]
        )
        res = checker.run_check(args)
        block = res.payload["cordon_degraded"]
        assert len(block["cordoned"]) == 1
        assert len(block["skipped_over_cap"]) == 1

    def test_failed_sweep_outranks_degraded_for_budget(self, tmp_path,
                                                       fake_api):
        # tpu-0 has DEAD chips, tpu-1 a slow link; one cordon of budget.
        nodes = _tpu_nodes(2)
        reports_dir = tmp_path / "probes"
        reports_dir.mkdir()
        (reports_dir / "tpu-0.json").write_text(json.dumps({
            "ok": False, "level": "mesh", "hostname": "tpu-0",
            "written_at": time.time(), "error": "mesh link dead",
        }))
        _mesh_reports(tmp_path, {"tpu-1": ["t0/0"]}, name="probes")
        args = cli.parse_args(
            [
                "--nodes-json", _nodes_json(tmp_path, nodes),
                "--probe-results", str(reports_dir),
                "--kubeconfig", fake_api["kubeconfig"],
                "--cordon-failed", "--cordon-degraded", "--cordon-max", "1",
                "--slice-floor-pct", "10",
                "--json",
            ]
        )
        res = checker.run_check(args)
        assert res.payload["cordon"]["cordoned"] == ["tpu-0"]
        assert res.payload["cordon_degraded"]["cordoned"] == []
        assert res.payload["cordon_degraded"]["skipped_over_cap"] == ["tpu-1"]


class TestLinkDriftChannel:
    def _run_rounds(self, tmp_path, rounds):
        """One checker round per entry; entry = {host: [drifting links]}."""
        nodes = _tpu_nodes(1)
        results = []
        for i, drifting in enumerate(rounds):
            d = tmp_path / f"probes{i}"
            d.mkdir()
            for host in ("tpu-0",):
                links = {}
                for name in ("t0/0", "t0/1"):
                    p50 = 300.0 if name in drifting.get(host, ()) else 10.0
                    links[name] = {"verdict": "OK", "p50_us": p50,
                                   "p99_us": p50 + 5.0, "budget_us": 400.0}
                (d / f"{host}.json").write_text(json.dumps({
                    "ok": True, "level": "mesh", "hostname": host,
                    "written_at": time.time(), "error": None,
                    "mesh_ok": True, "mesh_degraded": False,
                    "mesh_n_links": 2, "mesh_latency_us": 10.0,
                    "collective_legs_ok": {
                        "psum_ok": True, "all_gather_ok": True,
                        "reduce_scatter_ok": True, "links": links,
                    },
                }))
            args = cli.parse_args([
                "--nodes-json", _nodes_json(tmp_path, nodes),
                "--probe-results", str(d),
                "--history", str(tmp_path / "h.jsonl"),
                "--analytics", str(tmp_path / "ana"),
                "--json",
            ])
            results.append(checker.run_check(args))
        return results

    def test_link_drift_promotes_slice_to_suspect(self, tmp_path):
        # p50=300 >= 0.5*400 drifts; three net drifting rounds fire.
        results = self._run_rounds(
            tmp_path, [{"tpu-0": ["t0/1"]}] * 4
        )
        fired = [
            (i, p)
            for i, res in enumerate(results)
            for p in res.payload["analytics"]["predictions"]
            if "link" in p
        ]
        assert fired, "drifting link never detected"
        round_i, pred = fired[0]
        assert pred["link"].endswith("t0/1")
        assert pred["promoted"] == ["tpu-0"]
        # Promotion is visible in the round's history gauges...
        assert results[round_i].payload["history"]["states"][SUSPECT] == 1
        # ...but never accelerates condemnation: the node is not
        # cordon-eligible and later healthy rounds recover it.
        assert results[-1].payload["history"]["states"][FAILED] == 0

    def test_steady_links_never_fire(self, tmp_path):
        results = self._run_rounds(tmp_path, [{}] * 5)
        assert all(
            not res.payload["analytics"]["predictions"] for res in results
        )
