"""Chaos simulator acceptance (DESIGN.md §18).

Three contracts:

* **Determinism** — the same ``(scenario, seed)`` replays byte-identically:
  report JSON and the canonical event log, across in-process runs AND
  across cold CLI subprocesses (the acceptance criterion's form).
* **The grid is green** — every named scenario runs its invariant matrix
  end-to-end through real checker/aggregator machinery and passes,
  including the mass-cordon-storm budget/floor proof asserted on the
  simulated apiserver's request log (the PR 11 technique).
* **The matrix actually bites** — a deliberately injected over-budget
  actuation (cordon PATCHes behind the budget engine's back) is caught
  AND named by the report, so a green grid is evidence, not decoration.

Wall-clock note: scenarios pace through the simulator's injectable clock
(virtual sleeps are free); the only real waits are bounded polls on live
watch-reader threads inside the scenarios themselves.
"""

import json
import subprocess
import sys

import pytest

from tpu_node_checker.sim.engine import ScenarioError, run_scenario
from tpu_node_checker.sim.scenarios import SCENARIOS

SEED = 7


class TestDeterminism:
    def test_same_seed_twice_is_byte_identical(self):
        first = run_scenario("flap-storm", SEED)
        second = run_scenario("flap-storm", SEED)
        assert first.report_json == second.report_json
        assert first.events == second.events

    def test_different_seed_synthesizes_a_different_world(self):
        # Not a determinism requirement per se, but the replay handle must
        # actually steer the world: seeds 7 and 8 must not collapse onto
        # one fleet (the flapper assignment is rng-sampled).
        a = run_scenario("mass-cordon-storm", 7)
        b = run_scenario("mass-cordon-storm", 8)
        assert a.ok and b.ok
        assert a.events[0] != b.events[0]  # the fleet line names the failed sets

    def test_report_carries_no_wall_time(self):
        result = run_scenario("torn-slice", SEED)
        text = result.report_json
        # Timings exist for bench (round_ms) but must never enter the
        # replay-pinned report.
        assert result.round_ms, "wall timings should be measured"
        assert "ms" not in json.loads(text).get("params", {})
        assert "ts" not in json.loads(text)
        assert "duration" not in text


class TestScenarioGrid:
    @pytest.mark.parametrize("name", sorted(SCENARIOS))
    def test_scenario_runs_green(self, name):
        result = run_scenario(name, SEED)
        failed = [v for v in result.report["invariants"] if not v["ok"]]
        assert result.ok and not failed, failed
        # Every invariant the scenario declares actually ran.
        ran = {v["name"] for v in result.report["invariants"]}
        assert ran == set(SCENARIOS[name].invariants)

    def test_mass_cordon_storm_proves_budget_and_floor_server_side(self):
        result = run_scenario("mass-cordon-storm", SEED)
        by_name = {v["name"]: v for v in result.report["invariants"]}
        assert by_name["disruption-budget"]["ok"]
        assert by_name["slice-floor"]["ok"]
        assert by_name["denials-visible"]["ok"]
        # The rounds detail carries the server-side actuation log the
        # invariants were graded on: bounded, and never silent.
        patches = [r.get("patches") or [] for r in result.report["rounds"]]
        assert all(len(p) <= 2 for p in patches)
        assert sum(len(p) for p in patches) == 4  # 2 per slice = the floors

    def test_unknown_scenario_fails_loudly(self):
        with pytest.raises(ScenarioError):
            run_scenario("nope", SEED)

    def test_untunable_override_fails_loudly(self):
        with pytest.raises(ScenarioError):
            run_scenario("api-brownout", SEED, rounds=12)


class TestMatrixBites:
    def test_injected_over_budget_actuation_is_caught_and_named(self):
        result = run_scenario("mass-cordon-storm", SEED,
                              sabotage="over-budget")
        assert not result.ok
        failed = {v["name"] for v in result.report["invariants"]
                  if not v["ok"]}
        assert "disruption-budget" in failed
        assert "slice-floor" in failed
        budget = next(v for v in result.report["invariants"]
                      if v["name"] == "disruption-budget")
        # The verdict NAMES the breach (round + count), not just a flag.
        assert "over the 2/round budget" in budget["detail"]


class TestSimulateCli:
    def _run(self, *argv):
        return subprocess.run(
            [sys.executable, "-m", "tpu_node_checker", "simulate", *argv],
            capture_output=True, text=True, timeout=120,
        )

    def test_cold_cli_twice_is_byte_identical_and_green(self):
        runs = [
            self._run("--seed", str(SEED), "--scenario", "flap-storm",
                      "--report", "json")
            for _ in range(2)
        ]
        assert runs[0].returncode == 0, runs[0].stderr
        assert runs[0].stdout == runs[1].stdout
        doc = json.loads(runs[0].stdout)
        assert doc["ok"] is True
        assert doc["schema"] == 1
        assert doc["events_digest"].startswith("sha256:")
        assert all(v["ok"] for v in doc["invariants"])

    def test_list_scenarios_names_the_grid(self):
        proc = self._run("--list-scenarios")
        assert proc.returncode == 0
        for name in SCENARIOS:
            assert name in proc.stdout

    def test_unknown_scenario_is_a_usage_error(self):
        proc = self._run("--seed", "1", "--scenario", "nope")
        assert proc.returncode == 2
        assert "unknown scenario" in proc.stderr
