"""Slack retry state-machine tests (contract: check-gpu-node.py:47-111,142-157).

The HTTP boundary is faked with injectable ``post``/``sleep`` so every branch
of the retry classifier runs without a network or wall-clock delay.
"""

import requests

from tpu_node_checker import notify


class FakeResponse:
    def __init__(self, status_code):
        self.status_code = status_code


def make_post(script):
    """``script`` is a list of status codes or exceptions, consumed in order."""
    calls = []

    def post(url, json=None, timeout=None):
        calls.append({"url": url, "json": json, "timeout": timeout})
        action = script.pop(0)
        if isinstance(action, Exception):
            raise action
        return FakeResponse(action)

    post.calls = calls
    return post


def no_sleep(_):
    pass


class TestGating:
    def test_no_url_never_sends(self):
        assert not notify.should_send_slack_message(None, False, healthy=True)
        assert not notify.should_send_slack_message("", True, healthy=False)

    def test_only_on_error(self):
        url = "https://hooks.slack.example/x"
        assert notify.should_send_slack_message(url, True, healthy=False)
        assert not notify.should_send_slack_message(url, True, healthy=True)

    def test_always_when_not_gated(self):
        url = "https://hooks.slack.example/x"
        assert notify.should_send_slack_message(url, False, healthy=False)
        assert notify.should_send_slack_message(url, False, healthy=True)

    def test_flag_beats_env(self, monkeypatch):
        monkeypatch.setenv("SLACK_WEBHOOK_URL", "https://env.example")
        assert notify.get_slack_webhook_url("https://flag.example") == "https://flag.example"
        assert notify.get_slack_webhook_url(None) == "https://env.example"
        monkeypatch.delenv("SLACK_WEBHOOK_URL")
        assert notify.get_slack_webhook_url(None) is None


class TestRetryStateMachine:
    URL = "https://hooks.slack.example/x"

    def test_success_first_try(self):
        post = make_post([200])
        assert notify.send_slack_message(self.URL, "m", post=post, sleep=no_sleep)
        assert len(post.calls) == 1
        assert post.calls[0]["json"]["text"] == "m"
        assert post.calls[0]["timeout"] == notify.DEFAULT_TIMEOUT_S

    def test_non_200_retries_then_succeeds(self):
        # HTTP non-200 falls through to retry (check-gpu-node.py:83-84).
        post = make_post([500, 500, 200])
        assert notify.send_slack_message(self.URL, "m", post=post, sleep=no_sleep)
        assert len(post.calls) == 3

    def test_non_200_exhausts_retries(self):
        post = make_post([500, 500, 500, 500])
        assert not notify.send_slack_message(self.URL, "m", post=post, sleep=no_sleep)
        assert len(post.calls) == 4  # max_retries=3 → 4 attempts

    def test_connection_reset_retries(self):
        # Only reset/abort connection errors retry (check-gpu-node.py:86-99).
        post = make_post(
            [requests.exceptions.ConnectionError("Connection reset by peer"), 200]
        )
        assert notify.send_slack_message(self.URL, "m", post=post, sleep=no_sleep)
        assert len(post.calls) == 2

    def test_connection_aborted_retries(self):
        post = make_post(
            [requests.exceptions.ConnectionError("('Connection aborted.', ...)"), 200]
        )
        assert notify.send_slack_message(self.URL, "m", post=post, sleep=no_sleep)
        assert len(post.calls) == 2

    def test_other_connection_error_fails_immediately(self):
        post = make_post([requests.exceptions.ConnectionError("Name or service not known")])
        assert not notify.send_slack_message(self.URL, "m", post=post, sleep=no_sleep)
        assert len(post.calls) == 1

    def test_other_request_exception_fails_immediately(self):
        post = make_post([requests.exceptions.InvalidURL("bad url")])
        assert not notify.send_slack_message(self.URL, "m", post=post, sleep=no_sleep)
        assert len(post.calls) == 1

    def test_retry_delay_passed_to_sleep(self):
        sleeps = []
        post = make_post(
            [requests.exceptions.ConnectionError("Connection reset by peer"), 200]
        )
        notify.send_slack_message(
            self.URL, "m", post=post, sleep=sleeps.append, retry_delay=7.5
        )
        assert sleeps == [7.5]

    def test_non_200_retries_immediately_without_sleep(self):
        # Reference parity (check-gpu-node.py:83-84): non-200 falls through
        # the loop with NO sleep — retry_delay pacing belongs only to the
        # connection-error branch (:92).  A 500-ing webhook must not add
        # max_retries × retry_delay seconds to a watch round.
        sleeps = []
        post = make_post([500, 500, 500, 500])
        assert not notify.send_slack_message(
            self.URL, "m", post=post, sleep=sleeps.append, retry_delay=30.0
        )
        assert len(post.calls) == 4
        assert sleeps == []

    def test_retry_count_zero_single_attempt(self):
        post = make_post([500])
        assert not notify.send_slack_message(
            self.URL, "m", post=post, sleep=no_sleep, max_retries=0
        )
        assert len(post.calls) == 1
