"""--calibrate: produce site-measured TNC_PERF_EXPECT (VERDICT r04 next #4).

The dispatch-overhead gate deliberately refuses built-in-table grading on
transports where wall-clock figures time the transport (tunneled PJRT), and
``TNC_PERF_EXPECT`` grades anywhere — but nothing *produced* that JSON; each
site had to hand-measure.  ``--calibrate N`` closes the loop: N probe reps,
robust median per metric, margin, JSON on stdout (or a file).
"""

import json

import pytest

from tests import fixtures as fx  # noqa: F401 — import parity with suite style
from tpu_node_checker import cli
from tpu_node_checker.probe.floors import (
    DEFAULT_CALIBRATION_MARGIN,
    calibrate_expectations,
    grade_floors,
)
from tpu_node_checker.probe.liveness import ProbeResult, run_local_probe


class TestCalibrateExpectations:
    def test_median_and_margin(self):
        samples = [
            {"matmul_tflops": 10.0, "hbm_gbps": 100.0},
            {"matmul_tflops": 30.0, "hbm_gbps": 90.0},
            {"matmul_tflops": 12.0, "hbm_gbps": 1e9},  # straggler rep
        ]
        out = calibrate_expectations(samples, margin=0.9)
        assert out["matmul_tflops"] == pytest.approx(0.9 * 12.0)
        assert out["hbm_gbps"] == pytest.approx(0.9 * 100.0)

    def test_even_sample_count_averages_middle_pair(self):
        out = calibrate_expectations(
            [{"matmul_tflops": 10.0}, {"matmul_tflops": 20.0}], margin=1.0
        )
        assert out["matmul_tflops"] == pytest.approx(15.0)

    def test_soak_median_lifts_to_sustained(self):
        out = calibrate_expectations(
            [{"matmul_tflops": 10.0, "soak": {"tflops_median": 8.0}}],
            margin=1.0,
        )
        assert out["sustained_tflops"] == pytest.approx(8.0)

    def test_garbage_values_filtered(self):
        out = calibrate_expectations(
            [
                {"matmul_tflops": float("nan"), "hbm_gbps": -1.0,
                 "int8_tops": True, "ring_link_gbps": "fast"},
                {"matmul_tflops": 10.0},
            ],
            margin=1.0,
        )
        assert out == {"matmul_tflops": 10.0}

    def test_no_measurable_metrics_is_empty(self):
        assert calibrate_expectations([{"device_count": 8}]) == {}

    def test_bad_margin_raises(self):
        for margin in (0, -0.5, 1.5):
            with pytest.raises(ValueError, match="margin"):
                calibrate_expectations([{"matmul_tflops": 1.0}], margin=margin)

    def test_calibrated_expectations_grade_through_dispatch_gate(self):
        # The whole point: explicit expectations grade where the built-in
        # table self-disqualifies (65 ms tunneled dispatch overhead).
        expect = calibrate_expectations([{"matmul_tflops": 2.0}])
        healthy = grade_floors(
            ["TPU v5e"], "tpu", {"matmul_tflops": 1.9},
            expectations=expect, dispatch_overhead_ms=65.0,
        )
        assert healthy["ok"] is True and healthy["generation"] == "custom"
        throttled = grade_floors(
            ["TPU v5e"], "tpu", {"matmul_tflops": 0.1},
            expectations=expect, dispatch_overhead_ms=65.0,
        )
        assert throttled["failed"] == ["matmul_tflops"]


def _fake_probe(monkeypatch, values, fail_at=None):
    """run_local_probe double: rep i returns values[i] as matmul_tflops."""
    calls = []

    def fake(**kw):
        i = len(calls)
        calls.append(kw)
        if fail_at is not None and i == fail_at:
            return ProbeResult(
                ok=False, level="compute", hostname="h", elapsed_ms=1.0,
                device_count=8, error="chip dead",
            )
        return ProbeResult(
            ok=True, level="compute", hostname="h", elapsed_ms=1.0,
            device_count=8, platform="cpu",
            details={"matmul_tflops": values[i], "hbm_gbps": 50.0},
        )

    monkeypatch.setattr("tpu_node_checker.probe.run_local_probe", fake)
    return calls


class TestCalibrateCli:
    def test_stdout_json_is_margin_adjusted_median(self, monkeypatch, capsys):
        _fake_probe(monkeypatch, [10.0, 14.0, 12.0])
        code = cli.main(["--calibrate", "3", "--probe-level", "compute"])
        captured = capsys.readouterr()
        assert code == 0
        expect = json.loads(captured.out)
        assert expect["matmul_tflops"] == pytest.approx(
            DEFAULT_CALIBRATION_MARGIN * 12.0
        )
        assert expect["hbm_gbps"] == pytest.approx(
            DEFAULT_CALIBRATION_MARGIN * 50.0
        )
        # Per-rep telemetry goes to stderr — stdout stays pipeable JSON.
        assert "rep 3/3" in captured.err
        assert "TNC_PERF_EXPECT" in captured.err

    def test_failed_rep_aborts_without_json(self, monkeypatch, capsys):
        _fake_probe(monkeypatch, [10.0, 14.0, 12.0], fail_at=1)
        code = cli.main(["--calibrate", "3", "--probe-level", "compute"])
        captured = capsys.readouterr()
        assert code == 3
        assert captured.out == ""  # a sick host must never bless a floor
        assert "refusing to calibrate" in captured.err

    def test_calibrate_out_writes_file_atomically(
        self, monkeypatch, tmp_path, capsys
    ):
        _fake_probe(monkeypatch, [10.0])
        out = tmp_path / "expect.json"
        code = cli.main([
            "--calibrate", "1", "--probe-level", "compute",
            "--calibrate-out", str(out), "--calibrate-margin", "1.0",
        ])
        assert code == 0
        assert json.loads(out.read_text())["matmul_tflops"] == pytest.approx(10.0)
        assert capsys.readouterr().out == ""
        assert not out.with_suffix(".json.tmp").exists()

    def test_reps_disable_floor_grading_during_calibration(self, monkeypatch):
        calls = _fake_probe(monkeypatch, [10.0, 11.0])
        assert cli.main(["--calibrate", "2", "--probe-level", "compute"]) == 0
        assert all(kw.get("perf_floor") == 0 for kw in calls)

    def test_flag_guards(self, capsys):
        for argv in (
            ["--calibrate", "2"],  # enumerate level
            ["--calibrate", "0", "--probe-level", "compute"],
            ["--calibrate", "2", "--probe-level", "compute", "--json"],
            ["--calibrate", "2", "--probe-level", "compute", "--probe"],
            ["--calibrate", "2", "--probe-level", "compute",
             "--perf-floor", "0.4"],
            ["--calibrate", "2", "--probe-level", "compute",
             "--calibrate-margin", "1.5"],
            ["--calibrate-out", "/tmp/x.json"],
            ["--calibrate-margin", "0.8", "--probe", "--probe-level", "compute"],
            ["--selftest", "--calibrate", "2"],
        ):
            with pytest.raises(SystemExit) as e:
                cli.parse_args(argv)
            assert e.value.code == 2, argv
            capsys.readouterr()

    def test_soak_calibration_is_reachable(self):
        # --probe-soak composes with --calibrate (sustained_tflops is a
        # calibratable metric); the soak guard must not demand --probe.
        args = cli.parse_args([
            "--calibrate", "2", "--probe-level", "compute",
            "--probe-soak", "5",
        ])
        assert args.calibrate == 2 and args.probe_soak == 5.0
        assert args.calibrate_margin == pytest.approx(
            DEFAULT_CALIBRATION_MARGIN
        )


class TestCalibrateEndToEnd:
    def test_calibrate_then_probe_grades_instead_of_skipping(
        self, monkeypatch, shared_compute_probe
    ):
        # The real probe child on the CPU mesh: the built-in table skips
        # (platform cpu), but calibrated expectations grade — healthy passes,
        # and a throttle rehearsal against the same expectations fails.
        expect = calibrate_expectations([shared_compute_probe.to_dict()])
        assert expect["matmul_tflops"] > 0
        monkeypatch.setenv("TNC_PERF_EXPECT", json.dumps(expect))
        graded = run_local_probe(level="compute", timeout_s=300)
        assert graded.ok, graded.error
        floor = graded.details["perf_floor"]
        assert floor["ok"] is True and floor["generation"] == "custom"
        assert "skipped" not in floor
